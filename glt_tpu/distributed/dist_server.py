"""Sampling server: owns the dataset, produces batches for remote clients.

Rebuild of ``distributed/dist_server.py``: the reference's server owns a
DistDataset plus a pool of mp producers + shm buffers, and clients RPC
``create_sampling_producer / start_new_epoch_sampling /
fetch_one_sampled_message / destroy`` over torch RPC (:38-144).  The TPU
build speaks a small length-prefixed TCP protocol instead (JSON control
frames + TensorMap-serialized sample frames) — the transport the zero-
dependency host runtime actually needs; RDMA-class speed on-host comes from
the shm channel path, and cross-host bulk data rides the same socket.

Protocol (all frames ``u32 kind | u64 len | payload``):
  kind 0: JSON control request/response
  kind 1: ``u64 seq`` + serialized SampleMessage

Fault tolerance (beyond the reference, SURVEY §5):

* **ack-based resume** — every sampled message carries a per-producer,
  per-epoch monotonic sequence number; the server retains sent-but-unacked
  messages in a small replay window, so a client that reconnects after a
  dropped socket re-fetches exactly the batches it never received
  (``fetch_one_sampled_message`` carries ``ack``, the highest seq the
  client has contiguously received).
* **producer leases** — any request naming a producer renews its lease;
  a reaper thread GCs producers whose lease expired (mp worker fleet and
  shm segment included), so a client that crashes without calling
  ``destroy_sampling_producer`` cannot leak server resources.
* **structured errors** — recoverable request failures are JSON
  ``{"error": ..., "code": ...}`` responses on a *still-usable*
  connection; only protocol desync closes the session.
"""
from __future__ import annotations

import collections
import json
import queue
import socket
import struct
import threading
import time
from typing import Callable, Deque, Dict, Optional, Sequence, Tuple

import numpy as np

from ..channel.base import QueueSourceDied, bounded_get, bounded_put
from ..channel.serialization import deserialize, serialize
from ..obs import flight as _flight
from ..obs import metrics as _metrics
from ..obs import propagate as _prop
from ..obs.trace import auto_trace, auto_trace_export
from ..obs.trace import current as _current_tracer
from ..obs.trace import span as _span
from ..testing.faults import FaultPlan, ProducerKilled

# Server metrics (docs/observability.md "glt.server.*"): the production
# window into the PR-4 fault-tolerance machinery (seqs, replays, leases).
# Counters move only while ``obs.metrics`` is enabled; the ``get_metrics``
# op serves the Prometheus text exposition either way.
_M_MESSAGES = _metrics.counter(
    "glt.server.messages_sent", "sampled message frames sent")
_M_REPLAYS = _metrics.counter(
    "glt.server.replays", "unacked messages resent from the replay window")
_M_REAPED = _metrics.counter(
    "glt.server.producers_reaped", "producers GC'd by lease expiry")
_M_CREATED = _metrics.counter(
    "glt.server.producers_created", "sampling producers created")
_M_ERRORS = _metrics.counter(
    "glt.server.request_errors", "structured per-request failures")

# Per-request latency decomposition (docs/observability.md "Server-side
# latency decomposition"): where a fetch's wall time goes, server-side.
# snapshot() derives p50/p95/p99 per stage — the SLO groundwork the
# serving path (ROADMAP item 3) reads.
_H_QUEUE_WAIT = _metrics.histogram(
    "glt.server.queue_wait_ms",
    "fetch blocked waiting for the producer buffer (queue wait)")
_H_SAMPLE = _metrics.histogram(
    "glt.server.sample_ms", "producer-side sampling wall per batch")
_H_SERIALIZE = _metrics.histogram(
    "glt.server.serialize_ms",
    "batch flatten+serialize wall per message")
_H_SEND = _metrics.histogram(
    "glt.server.send_ms", "sampled-frame socket send wall")
_H_REPLAY = _metrics.histogram(
    "glt.server.replay_ms",
    "replay-served fetches: window lookup + resend wall")

_KIND_JSON = 0
_KIND_MSG = 1
# Serving responses (glt_tpu.serving): one serialized per-request
# SampleMessage, no sequence number — subgraph requests are stateless.
_KIND_SUB = 2

# Reject frames above this many payload bytes unless configured otherwise:
# a corrupt (or hostile) u64 length must fail the frame, not drive an
# unbounded allocation.
DEFAULT_MAX_FRAME_BYTES = 1 << 30

DEFAULT_LEASE_SECS = 300.0
DEFAULT_REPLAY_WINDOW = 8

# Ops that only a current-protocol server understands: an older server
# answers each of them with its unknown-op fatal error, so every client
# call site must degrade (return None, or pin the peer to legacy
# routing) instead of surfacing a new failure mode.  ``fleet_hello`` is
# the negotiation itself; the observability pulls shipped after the
# protocol froze ride the same contract.  gltlint reads this set to
# assign per-op minimum protocol versions (GLT026, ``--format=optable``,
# and the mixed-version matrix in docs/distributed.md).
POST_HELLO_OPS = frozenset({
    "fleet_hello",
    "fleet_shed",
    "flight_dump",
    "profile_capture",
})


class ProtocolError(RuntimeError):
    """The framed byte stream is invalid (bad length, truncated header)."""


class RequestError(RuntimeError):
    """A structured, per-request failure: reported to the client as
    ``{"error": ..., "code": ...}`` without closing the connection, so the
    client can distinguish e.g. a GC'd producer lease (``unknown_producer``)
    from a crashed server.  ``extra`` keys ride alongside in the error
    response (the serving path's ``retry_after_ms`` backoff hint)."""

    def __init__(self, message: str, code: str, **extra):
        super().__init__(message)
        self.code = code
        self.extra = dict(extra)


def send_frame(sock, kind: int, payload: bytes) -> None:
    sock.sendall(struct.pack("<IQ", kind, len(payload)) + payload)


def recv_frame(sock, max_len: int = DEFAULT_MAX_FRAME_BYTES):
    hdr = _recv_exact(sock, 12)
    if hdr is None:
        return None, None
    kind, length = struct.unpack("<IQ", hdr)
    if length > max_len:
        raise ProtocolError(
            f"frame length {length} exceeds the {max_len}-byte bound "
            f"(corrupt stream or hostile peer)")
    data = _recv_exact(sock, length)
    return kind, data


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _Producer:
    """Server-side sampling producer filling a bounded buffer
    (the reference's producer + shm buffer pair, dist_server.py:83-116).

    Two backends, chosen by ``num_workers``:
      * 0 — one in-server thread driving a collocated NeighborLoader;
      * >0 — an :class:`MpSamplingProducer` worker fleet feeding a shm
        ring (the reference's mp producer pool on the server,
        dist_server.py:83-116), drained into the bounded buffer by a
        forwarder thread.  Requires the server's picklable
        ``dataset_builder``.

    Delivery bookkeeping: buffer items are ``(epoch, payload)`` pairs;
    ``fetch_next`` assigns each fresh payload a monotonic per-epoch seq,
    retains the last ``replay_window`` sent-but-unacked payloads for
    resume-after-reconnect, and re-homes items popped by a stale (dead-
    connection) reader thread so no batch is ever lost to a race.
    """

    def __init__(self, dataset, num_neighbors, input_nodes, batch_size,
                 buffer_capacity: int = 8, seed: int = 0,
                 num_workers: int = 0, dataset_builder=None,
                 builder_args: tuple = (),
                 channel_capacity_bytes: int = 64 * 1024 * 1024,
                 lease_secs: float = DEFAULT_LEASE_SECS,
                 replay_window: int = DEFAULT_REPLAY_WINDOW,
                 fault_plan: Optional[FaultPlan] = None):
        self.buffer: "queue.Queue" = queue.Queue(maxsize=buffer_capacity)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._mp_producer = None
        self._channel = None
        self._fault_plan = fault_plan
        # -- lease -----------------------------------------------------
        self.lease_secs = float(lease_secs)
        self.last_active = time.monotonic()
        # -- sequencing / replay --------------------------------------
        self.replay_window = max(1, int(replay_window))
        self._seq_lock = threading.Lock()
        self._epoch = 0
        self._next_seq = 0
        self._retained: Deque[Tuple[int, bytes]] = collections.deque()
        self._orphans: list = []
        self._error: Optional[Exception] = None
        # Wire context of the epoch's start request: producer spans (and
        # the mp workers, via the task payload) join this trace.
        self._trace_ctx: Optional[dict] = None
        if num_workers > 0:
            if dataset_builder is None:
                raise ValueError(
                    "num_workers > 0 needs the server started with a "
                    "picklable dataset_builder (init_server(..., "
                    "dataset_builder=...))")
            from ..channel import ShmChannel
            from .dist_options import MpSamplingWorkerOptions
            from .dist_sampling_producer import MpSamplingProducer

            self._channel = ShmChannel(
                capacity_bytes=channel_capacity_bytes)
            self._mp_producer = MpSamplingProducer(
                dataset_builder, builder_args, list(num_neighbors),
                np.asarray(input_nodes, np.int64), int(batch_size),
                MpSamplingWorkerOptions(num_workers=num_workers),
                self._channel, shuffle=True, seed=seed)
            self._mp_producer.init()
            nbatches = self._mp_producer.num_expected()
        else:
            from ..loader.node_loader import NeighborLoader

            self.loader = NeighborLoader(dataset, num_neighbors,
                                         input_nodes, batch_size=batch_size,
                                         shuffle=True, seed=seed)
            nbatches = len(self.loader)
        self._num_expected = nbatches

    def num_expected(self) -> int:
        return self._num_expected

    # -- lease --------------------------------------------------------
    def touch(self) -> None:
        self.last_active = time.monotonic()

    def lease_expired(self, now: float) -> bool:
        return (self.lease_secs > 0
                and now - self.last_active > self.lease_secs)

    def start_epoch(self, epoch: int = 0,
                    trace_ctx: Optional[dict] = None) -> None:
        if self._thread is not None:
            # Tell the previous epoch's thread to stop before joining: a
            # client that abandoned its epoch mid-way (early stopping)
            # leaves the thread wedged on the bounded buffer, and without
            # the stop signal this join would block 60s and then poison
            # the producer.
            self._stop.set()
            self._thread.join(timeout=60)
            if self._thread.is_alive():
                raise RequestError("previous epoch still producing",
                                   code="epoch_busy")
        self._stop.clear()
        # Drop anything a previous epoch left behind (in particular a
        # relayed error the client never fetched) so it cannot poison
        # this epoch's first fetch.
        while True:
            try:
                self.buffer.get_nowait()
            except queue.Empty:
                break
        with self._seq_lock:
            self._epoch = int(epoch)
            self._next_seq = 0
            self._retained.clear()
            self._orphans.clear()
            self._error = None
            self._trace_ctx = trace_ctx
        if self._mp_producer is not None:
            self._mp_producer.produce_all(trace_ctx=trace_ctx)
            self._thread = threading.Thread(target=self._forward_mp,
                                            args=(int(epoch),), daemon=True)
        else:
            self._thread = threading.Thread(target=self._run,
                                            args=(int(epoch),), daemon=True)
        self._thread.start()

    def _run(self, epoch: int) -> None:
        from .sample_message import batch_to_message

        ctx = self._trace_ctx or {}
        # Loader failures are relayed to the fetching client (same
        # contract as _forward_mp) instead of dying silently here.
        try:
            batches = iter(self.loader)
            for i in range(self._num_expected):
                with _span("producer.sample_batch", epoch=epoch,
                           index=i) as sp:
                    sp.link(ctx.get("tid"), ctx.get("sid"))
                    t0 = time.perf_counter()
                    try:
                        batch = next(batches)
                    except StopIteration:
                        break
                    _H_SAMPLE.observe((time.perf_counter() - t0) * 1e3)
                    with _H_SERIALIZE.time():
                        payload = serialize(batch_to_message(batch))
                # stop-aware put so a producer whose client vanished
                # mid-epoch exits instead of wedging on the bounded buffer
                # (and permanently poisoning this producer id).
                if not bounded_put(self.buffer, (epoch, payload),
                                   self._stop):
                    return
                if self._fault_plan is not None:
                    self._fault_plan.on_producer_put()
        except ProducerKilled:
            # Simulated crash (testing/faults.py): die exactly like an
            # unexpected thread death — no relay, no cleanup; the fetch
            # path's liveness recheck is what must surface this.
            return
        except Exception as e:  # noqa: BLE001 — relayed to client
            bounded_put(self.buffer, (epoch, e), self._stop)

    def _forward_mp(self, epoch: int) -> None:
        # iter_messages raises after max_respawns of fruitless worker
        # deaths; relay that to the fetching client instead of discarding
        # it in this daemon thread (which would hang the client forever).
        try:
            for msg in self._mp_producer.iter_messages():
                with _H_SERIALIZE.time():
                    payload = serialize(msg)
                if not bounded_put(self.buffer, (epoch, payload),
                                   self._stop):
                    return
        except Exception as e:  # noqa: BLE001 — relayed to client
            bounded_put(self.buffer, (epoch, e), self._stop)

    # -- sequenced fetch ----------------------------------------------
    def _epoch_alive(self) -> bool:
        t = self._thread
        return (t is not None and t.is_alive()
                and not self._stop.is_set())

    def _check_epoch(self, epoch: int) -> None:
        if epoch != self._epoch:
            raise RequestError(
                f"fetch for epoch {epoch} but producer is on epoch "
                f"{self._epoch}", code="stale_epoch")

    def _pop_current(self, epoch: int):
        """Pop the next item produced *for this epoch*: orphans first
        (items a dead connection's reader popped but could not deliver),
        then the buffer; items left over from an older epoch are dropped."""
        t_wait0 = time.perf_counter()
        while True:
            with self._seq_lock:
                self._check_epoch(epoch)
                if self._orphans:
                    _H_QUEUE_WAIT.observe(
                        (time.perf_counter() - t_wait0) * 1e3)
                    return self._orphans.pop(0)
            # Bounded wait with a liveness recheck (the GLT007 hang class):
            # if the epoch thread died between its last put and our get,
            # the client gets an error, not a blocked connection thread.
            # Each poll also renews the lease — a client waiting on a slow
            # batch is an active client.
            item_epoch, item = bounded_get(
                self.buffer, alive=self._epoch_alive, poll=0.25,
                on_wait=self.touch)
            with self._seq_lock:
                if item_epoch != self._epoch:
                    continue       # stale leftover from an older epoch
                if epoch != self._epoch:
                    # We are the stale reader: the epoch rolled while we
                    # were blocked.  Re-home the item for the live epoch.
                    self._orphans.append(item)
                    self._check_epoch(epoch)
            _H_QUEUE_WAIT.observe((time.perf_counter() - t_wait0) * 1e3)
            return item

    def fetch_next(self, ack: int, epoch: int) -> Tuple[int, bytes, bool]:
        """Return ``(seq, payload, replayed)`` — the resumable fetch.

        ``ack`` is the highest seq the client has contiguously received:
        everything at or below it is released from the replay window; the
        oldest retained seq above it (a message lost in flight on a dead
        connection) is re-sent before anything fresh is produced
        (``replayed=True``), so every batch of an epoch is delivered
        exactly once across arbitrarily many reconnects.
        """
        self.touch()
        with self._seq_lock:
            self._check_epoch(epoch)
            if self._error is not None:
                # Sticky: a sampling failure survives response loss and
                # reconnects until the next epoch resets it.
                raise RequestError(
                    f"server-side sampling failed: {self._error}",
                    code="sampling_failed")
            while self._retained and self._retained[0][0] <= ack:
                self._retained.popleft()
            resend = self._retained[0] if self._retained else None
        if resend is not None:
            # Sent but never received: resume from the oldest gap.
            _M_REPLAYS.inc()
            _flight.record("server.replay", msg_seq=resend[0],
                           epoch=epoch)
            tracer = _current_tracer()
            if tracer is not None:
                ctx = self._trace_ctx or {}
                tracer.instant("server.replay", seq=resend[0],
                               epoch=epoch, trace_id=ctx.get("tid"))
            return resend[0], resend[1], True
        try:
            item = self._pop_current(epoch)
        except QueueSourceDied:
            raise RequestError(
                "producer sampling thread died mid-epoch (or was stopped) "
                "before delivering every batch; restart the epoch",
                code="producer_dead") from None
        if isinstance(item, Exception):
            with self._seq_lock:
                self._error = item
            raise RequestError(f"server-side sampling failed: {item}",
                               code="sampling_failed")
        with self._seq_lock:
            if epoch != self._epoch:
                self._orphans.append(item)
                self._check_epoch(epoch)
            seq = self._next_seq
            self._next_seq += 1
            self._retained.append((seq, item))
            while len(self._retained) > self.replay_window:
                self._retained.popleft()
        return seq, item, False

    def stop(self) -> None:
        self._stop.set()
        if self._mp_producer is not None:
            # Order matters: shutdown() first sets the producer's stopping
            # flag so a forwarder blocked in channel.recv exits at its next
            # timeout, THEN the thread is joined, and the shm segment is
            # only unlinked once the forwarder is provably out of recv —
            # closing under its feet would be a native use-after-free.
            self._mp_producer.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=30)
        if self._mp_producer is not None:
            if self._thread is None or not self._thread.is_alive():
                self._channel.close()
            # else: leak the segment rather than unmap it under a live
            # reader; the process exiting reclaims it.


class DistServer:
    """Args mirror init_server (dist_server.py:158-190)."""

    def __init__(self, dataset, host: str = "127.0.0.1", port: int = 0,
                 dataset_builder=None, builder_args: tuple = (),
                 num_servers: int = 1, server_rank: int = 0,
                 num_clients: int = 0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 reap_interval: float = 0.25,
                 fault_plan: Optional[FaultPlan] = None,
                 enable_metrics: bool = False,
                 heartbeat_deadline: float = 10.0,
                 serving=None):
        from .dist_context import _set_default, make_server_context
        from .supervisor import Supervisor

        if enable_metrics:
            # Serving deployments opt in: flips the PROCESS-wide metrics
            # switch so the get_metrics exposition carries live counters.
            _metrics.enable()
        # GLT_OBS_TRACE_DIR: this process exports its own trace file at
        # shutdown; `python -m glt_tpu.obs merge` stitches it with the
        # client's and the workers' into one fleet trace.
        self._trace_export_path = auto_trace("server")

        self.dataset = dataset
        self._dataset_builder = dataset_builder
        self._builder_args = builder_args
        self.max_frame_bytes = int(max_frame_bytes)
        self._reap_interval = float(reap_interval)
        self._fault_plan = fault_plan
        # The server's own topology record; installed as the process
        # context only when none exists (several roles can share one
        # process in the single-host test topology — call
        # init_server_context explicitly to claim the global).
        self.context = make_server_context(num_servers, server_rank,
                                           num_clients)
        _set_default(self.context)
        # Fleet supervision (docs/distributed.md "Fleet supervision"):
        # clients/trainers report liveness via the `heartbeat` op on this
        # same control channel; `fleet_health` serves the structured
        # table.  Monitoring starts lazily with the first beat, so
        # heartbeat-free deployments pay nothing.
        self.supervisor = Supervisor(deadline_secs=heartbeat_deadline)
        # Inference serving front (glt_tpu.serving, docs/serving.md):
        # opt-in via init_server(serving=ServingOptions(...)).  The same
        # framed protocol carries the latency path — `subgraph_request`
        # answers with a _KIND_SUB frame, `serving_stats` with JSON.
        self.serving = None
        if serving is not None:
            from ..serving.front import ServingFront

            self.serving = ServingFront(dataset, serving,
                                        fault_plan=fault_plan)
        self._producers: Dict[int, _Producer] = {}
        # Live accepted sockets, tracked so kill() can sever them
        # abruptly (chaos testing: clients must see a raw transport
        # error, never a polite structured goodbye).
        self._live_conns: set = set()
        self._conns_lock = threading.Lock()
        # client_key -> producer id: a client that reconnects and
        # re-creates (its lease expired, or it restarted) first tears
        # down its previous producer instead of leaking it.
        self._client_keys: Dict[str, int] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.addr = self._sock.getsockname()
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        # Lease reaper: GCs producers whose client vanished without a
        # destroy (crash, network partition) — mp fleet + shm included.
        self._reaper_thread = threading.Thread(target=self._reap_loop,
                                               daemon=True)
        self._reaper_thread.start()

    # -- producer bookkeeping ---------------------------------------------
    def _get_producer(self, req: dict) -> _Producer:
        pid = req.get("producer_id")
        with self._lock:
            prod = self._producers.get(pid)
        if prod is None:
            raise RequestError(
                f"unknown or expired producer id {pid!r} (lease GC'd, "
                f"destroyed, or never created on this server)",
                code="unknown_producer")
        prod.touch()
        return prod

    def _reap_loop(self) -> None:
        while not self._stop.wait(self._reap_interval):
            now = time.monotonic()
            expired = []
            with self._lock:
                for pid in [p for p, prod in self._producers.items()
                            if prod.lease_expired(now)]:
                    expired.append((pid, self._producers.pop(pid)))
                for pid, _ in expired:
                    for ck in [k for k, v in self._client_keys.items()
                               if v == pid]:
                        del self._client_keys[ck]
            for _, prod in expired:
                prod.stop()
            if expired:
                _M_REAPED.inc(len(expired))
                _flight.record("server.producers_reaped",
                               producer_ids=[pid for pid, _ in expired])

    def live_producers(self) -> int:
        with self._lock:
            return len(self._producers)

    def metrics_text(self) -> str:
        """Prometheus text exposition of the whole ``glt.*`` namespace.

        Point-in-time gauges (live producer count) are refreshed here so
        a scrape always sees current occupancy; served to clients by the
        ``get_metrics`` op.
        """
        _metrics.gauge("glt.server.live_producers",
                       "producers currently registered"
                       ).set(self.live_producers())
        return _metrics.render_prometheus()

    # -- request handlers (cf. _call_func_on_server, dist_server.py:214) ---
    def _handle(self, req: dict, trace_ctx: Optional[dict] = None):
        op = req["op"]
        # Justified (GLT024): sent by notebooks/operator tooling and the
        # integration tests, not by any in-package client path.
        # gltlint: disable-next=unmatched-wire-op
        if op == "get_dataset_meta":
            g = self.dataset.get_graph()
            return {"num_nodes": g.num_nodes, "num_edges": g.num_edges,
                    "server_rank": self.context.rank,
                    "num_servers": self.context.world_size}
        if op == "create_sampling_producer":
            # Construct outside the lock: mp-producer setup (process spawn
            # + dataset rebuild) can take seconds and must not stall other
            # clients' create/destroy requests.
            prod = _Producer(
                self.dataset, req["num_neighbors"],
                np.asarray(req["input_nodes"], np.int64),
                req["batch_size"],
                buffer_capacity=req.get("buffer_capacity", 8),
                seed=req.get("seed", 0),
                num_workers=req.get("num_workers", 0),
                dataset_builder=self._dataset_builder,
                builder_args=self._builder_args,
                channel_capacity_bytes=req.get(
                    "channel_capacity_bytes", 64 * 1024 * 1024),
                lease_secs=req.get("lease_secs", DEFAULT_LEASE_SECS),
                replay_window=req.get("replay_window",
                                      DEFAULT_REPLAY_WINDOW),
                fault_plan=self._fault_plan)
            client_key = req.get("client_key")
            stale = None
            with self._lock:
                pid = self._next_id
                self._next_id += 1
                self._producers[pid] = prod
                if client_key:
                    old = self._client_keys.get(client_key)
                    if old is not None:
                        stale = self._producers.pop(old, None)
                    self._client_keys[client_key] = pid
            if stale is not None:
                # Same client re-created (reconnect after lease GC raced,
                # or a restart): its previous fleet must not leak.
                stale.stop()
            _M_CREATED.inc()
            _flight.record("server.producer_created", producer_id=pid,
                           num_workers=req.get("num_workers", 0))
            return {"producer_id": pid,
                    "num_expected": prod.num_expected()}
        if op == "heartbeat":
            # A fleet role reporting liveness (supervisor.HeartbeatSender).
            # Also renews the peer's producer lease when it names one: a
            # heartbeating client is an active client even between
            # fetches (long eval pauses, slow trainers).
            self.supervisor.beat(str(req.get("peer", "client")),
                                 step=req.get("step"))
            pid = req.get("producer_id")
            if pid is not None:
                with self._lock:
                    prod = self._producers.get(pid)
                if prod is not None:
                    prod.touch()
            return {"ok": True}
        if op == "fleet_health":
            return {"peers": self.supervisor.status(),
                    "live_producers": self.live_producers()}
        if op == "fleet_hello":
            # Router/controller handshake (docs/serving.md "Fleet"): a
            # fleet-aware replica answers with its protocol number and
            # whether serving is mounted; the caller's name is beaten
            # into the supervisor so replica-side `fleet_health` shows
            # the router as a peer.  A pre-19 replica answers this op
            # with its unknown-op fatal error — the router's cue to
            # degrade that replica to direct (legacy) routing.
            peer = str(req.get("peer", "router"))
            self.supervisor.beat(peer)
            return {"ok": True, "protocol": 1,
                    "serving": self.serving is not None,
                    "stale_after_s": self.supervisor.deadline_secs}
        if op == "fleet_shed":
            # Fleet-wide shed/reopen broadcast from the FleetController:
            # the alert dict is exactly what a local SloMonitor would
            # deliver, so one burn-rate evaluation at the controller
            # drives every replica's admission bound.  A pre-19 replica
            # fails this op fatally (the controller tolerates that).
            if self.serving is None:
                return {"ok": False, "enabled": False}
            self.serving.slo_alert(dict(req.get("alert") or {}))
            return {"ok": True, "enabled": True,
                    "shed_frac": self.serving.stats()["shed_frac"]}
        if op == "serving_stats":
            # Occupancy + rejection counters of the serving front
            # (docs/serving.md); enabled=False when serving is off so a
            # probe never needs to catch an error.
            if self.serving is None:
                return {"enabled": False}
            return {"enabled": True, **self.serving.stats()}
        # Justified (GLT024): consumed by the scrape sidecar over the
        # framed protocol (docs/observability.md), never by an
        # in-package client.
        # gltlint: disable-next=unmatched-wire-op
        if op == "get_metrics":
            # Prometheus-style text exposition (docs/observability.md):
            # a scrape sidecar (or a curl over the framed protocol) reads
            # the whole glt.* namespace — producer/lease/replay counters
            # included — without touching producer state.
            return {"text": self.metrics_text(),
                    "enabled": _metrics.enabled()}
        if op == "flight_dump":
            # On-demand black-box read (docs/observability.md "Flight
            # recorder"): the server's ring of structured events, as the
            # same JSON object the crash-time dump writes — so an
            # operator can pull a postmortem from a LIVE server, and
            # `obs merge` folds it with the clients' dumps.  A pre-13
            # server answers this op with its usual unknown-op fatal
            # error; the client helper degrades to None (mixed-version
            # contract, tests/test_server_client.py).
            _flight.record("server.flight_dump_served")
            snap = _flight.recorder().snapshot(reason="wire_op")
            if req.get("path"):
                # Optional server-side file dump beside the wire reply
                # (operator pulling artifacts off the server host).
                snap["path"] = _flight.dump_now("wire_op",
                                                path=str(req["path"]))
            return {"flight": snap}
        if op == "profile_capture":
            # Triggered XLA profiler capture on the SERVER host
            # (docs/observability.md "Triggered profiling"): a bounded
            # jax.profiler trace into `dir` (a fresh temp dir when
            # unset), indexed in the server's flight ring.  A pre-14
            # server answers with its unknown-op fatal error; the
            # client helper degrades to None (mixed-version contract).
            import tempfile

            from ..obs import profiler as _obs_profiler
            millis = min(float(req.get("millis", 50.0)),
                         _obs_profiler.MAX_CAPTURE_MILLIS)
            pdir = (str(req["dir"]) if req.get("dir")
                    else tempfile.mkdtemp(prefix="glt_profile_"))
            _flight.record("server.profile_capture_served",
                           dir=pdir, millis=millis)
            try:
                with _obs_profiler.capture(pdir, millis=millis,
                                           reason="wire_op"):
                    pass
            except Exception as e:  # noqa: BLE001 — structured reply,
                return {"ok": False, "error": repr(e)}  # not a close
            return {"ok": True, "dir": pdir, "millis": millis}
        if op == "start_new_epoch_sampling":
            self._get_producer(req).start_epoch(
                int(req.get("epoch", 0)), trace_ctx=trace_ctx)
            return {"ok": True}
        if op == "destroy_sampling_producer":
            with self._lock:
                prod = self._producers.pop(req["producer_id"], None)
                for ck in [k for k, v in self._client_keys.items()
                           if v == req["producer_id"]]:
                    del self._client_keys[ck]
            if prod is not None:
                prod.stop()
            return {"ok": True}
        if op == "exit":
            self._stop.set()
            return {"ok": True}
        raise ValueError(f"unknown op {op!r}")

    def _handle_subgraph(self, req: dict) -> bytes:
        """Admit one serving request, wait for its coalesced result, and
        return the serialized per-request SampleMessage.

        Every failure mode is a :class:`RequestError` (connection stays
        usable): serving disabled, admission rejection (``overloaded``
        with a ``retry_after_ms`` hint), deadline miss, engine fault,
        or a server-side wait-budget timeout."""
        from ..serving.errors import ServingError

        if self.serving is None:
            raise RequestError(
                "serving not enabled on this server; start it with "
                "init_server(..., serving=ServingOptions(...))",
                code="serving_disabled")
        deadline_ms = req.get("deadline_ms")
        try:
            pending = self.serving.submit(req.get("seeds", ()),
                                          deadline_ms=deadline_ms)
        except ServingError as e:
            raise RequestError(
                str(e), code=e.code,
                **({} if e.retry_after_ms is None
                   else {"retry_after_ms": e.retry_after_ms})) from None
        # Bounded wait (GLT007 discipline): the budget covers the
        # request's own deadline plus a full queue's service time; a
        # dispatcher wedged past that surfaces as a structured timeout,
        # not a stuck connection thread.
        if not pending.done.wait(
                timeout=self.serving.wait_budget_s(deadline_ms)):
            raise RequestError(
                "serving request timed out server-side (dispatcher "
                "overwhelmed or wedged)", code="serving_timeout")
        if pending.error is not None:
            e = pending.error
            raise RequestError(
                str(e), code=getattr(e, "code", "serving_failed"),
                **({} if e.retry_after_ms is None
                   else {"retry_after_ms": e.retry_after_ms}))
        return serialize(pending.message)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn) -> None:
        if self._fault_plan is not None:
            conn = self._fault_plan.wrap(conn)
        with self._conns_lock:
            self._live_conns.add(conn)
        try:
            while True:
                kind, data = recv_frame(conn, max_len=self.max_frame_bytes)
                if kind is None:
                    return
                tracer = _current_tracer()
                t_recv_us = tracer.now_us() if tracer is not None else None
                req = json.loads(data)
                # Trace context rides a reserved JSON key — a pre-trace
                # server reads only the keys it knows, so old/new peers
                # interoperate (mixed-version test); popped here so
                # request handlers never see it.
                ctx = _prop.extract(req)
                _metrics.counter(
                    "glt.server.requests", "requests handled, by op",
                    labels={"op": str(req.get("op"))}).inc()
                try:
                    if req["op"] == "fetch_one_sampled_message":
                        t_req0 = time.perf_counter()
                        with _span("server.fetch") as sp:
                            if ctx:
                                sp.link(ctx.get("tid"), ctx.get("sid"))
                            prod = self._get_producer(req)
                            seq, payload, replayed = prod.fetch_next(
                                int(req.get("ack", -1)),
                                int(req.get("epoch", 0)))
                            sp.set(seq=seq, replayed=replayed)
                            frame = struct.pack("<Q", seq) + payload
                            if ctx and tracer is not None:
                                # Clock echo as an append-only trailer —
                                # only on negotiated (context-carrying)
                                # requests, so an old client never sees
                                # trailer bytes.
                                frame = _prop.pack_trailer(
                                    frame, _prop.server_echo(
                                        tracer, t_recv_us))
                            with _H_SEND.time():
                                send_frame(conn, _KIND_MSG, frame)
                        if replayed:
                            _H_REPLAY.observe(
                                (time.perf_counter() - t_req0) * 1e3)
                        _M_MESSAGES.inc()
                    elif req["op"] == "subgraph_request":
                        # Latency path (glt_tpu.serving): this
                        # connection thread blocks on ITS request's
                        # completion only — the coalescer batches across
                        # however many connection threads are waiting,
                        # which is what makes the op multi-client safe.
                        with _span("server.subgraph") as sp:
                            if ctx:
                                sp.link(ctx.get("tid"), ctx.get("sid"))
                            frame = self._handle_subgraph(req)
                            sp.set(bytes=len(frame))
                            if ctx and tracer is not None:
                                frame = _prop.pack_trailer(
                                    frame, _prop.server_echo(
                                        tracer, t_recv_us))
                            send_frame(conn, _KIND_SUB, frame)
                    else:
                        with _span("server." + str(req["op"])) as sp:
                            if ctx:
                                sp.link(ctx.get("tid"), ctx.get("sid"))
                            resp = self._handle(req, trace_ctx=ctx)
                            if ctx and tracer is not None:
                                resp[_prop.WIRE_KEY] = _prop.server_echo(
                                    tracer, t_recv_us)
                            send_frame(conn, _KIND_JSON,
                                       json.dumps(resp).encode())
                except RequestError as e:
                    # Structured per-request failure: report it and keep
                    # the connection serving — the framed stream is still
                    # in sync.
                    _M_ERRORS.inc()
                    _flight.record("server.request_error",
                                   op=str(req.get("op")), code=e.code,
                                   msg=str(e)[:200])
                    send_frame(conn, _KIND_JSON, json.dumps(
                        {"error": str(e), "code": e.code,
                         **e.extra}).encode())
        except Exception as e:  # desync/socket errors end the session
            # "protocol" marks a desynced stream: the client treats it as
            # retryable (reconnect resyncs framing, the replay window
            # resumes delivery); anything else is a terminal server error.
            code = "protocol" if isinstance(e, ProtocolError) else "fatal"
            _flight.record("server.conn_error", code=code,
                           exc=type(e).__name__, msg=str(e)[:200])
            try:
                send_frame(conn, _KIND_JSON, json.dumps(
                    {"error": str(e), "code": code}).encode())
            except OSError:
                pass
        finally:
            with self._conns_lock:
                self._live_conns.discard(conn)
            conn.close()

    def wait_for_exit(self, timeout: Optional[float] = None) -> None:
        self._stop.wait(timeout)

    def kill(self) -> None:
        """Die like a crashed process (chaos testing): stop accepting,
        sever every live connection mid-stream, stop the serving
        dispatcher.  No structured goodbyes — in-flight clients see a
        raw transport error (ECONNRESET/EOF), which is exactly the
        failure the fleet router's failover path must absorb.  Producer
        teardown is left to the lease reaper, as a real crash would."""
        self._stop.set()
        _flight.record("server.killed", addr=list(self.addr))
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._live_conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self.serving is not None:
            self.serving.stop()

    def shutdown(self) -> None:
        self._stop.set()
        if self.serving is not None:
            # Fail queued serving requests structurally before tearing
            # down producers — their connection threads are waiting.
            self.serving.stop()
        # Stop every live producer: with the mp backend each one owns a
        # worker-process fleet and a shm ring that would otherwise outlive
        # the client that forgot to destroy it.
        with self._lock:
            producers = list(self._producers.values())
            self._producers.clear()
            self._client_keys.clear()
        for prod in producers:
            prod.stop()
        try:
            self._sock.close()
        except OSError:
            pass
        auto_trace_export(self._trace_export_path)


def init_server(dataset, host: str = "127.0.0.1", port: int = 0,
                dataset_builder=None, builder_args: tuple = (),
                num_servers: int = 1, server_rank: int = 0,
                num_clients: int = 0,
                max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                reap_interval: float = 0.25,
                fault_plan: Optional[FaultPlan] = None,
                enable_metrics: bool = False,
                heartbeat_deadline: float = 10.0,
                serving=None) -> DistServer:
    """Start a sampling server (cf. init_server, dist_server.py:158-190).

    Pass a picklable ``dataset_builder`` (+``builder_args``) to enable
    mp producer pools for clients requesting
    ``RemoteSamplingWorkerOptions(num_workers > 0)``.
    ``num_servers``/``server_rank``/``num_clients`` record the fleet
    topology in this process's :class:`~.dist_context.DistContext`.
    ``max_frame_bytes`` bounds inbound frame payloads (protocol error
    beyond it); ``fault_plan`` wires a deterministic
    :class:`~glt_tpu.testing.faults.FaultPlan` into every accepted
    connection and producer thread (chaos testing only).
    ``enable_metrics=True`` flips the process-wide
    :mod:`glt_tpu.obs.metrics` switch so the ``get_metrics`` op's
    Prometheus exposition carries live ``glt.server.*`` counters.
    ``serving=ServingOptions(...)`` additionally mounts the inference
    serving front (:mod:`glt_tpu.serving`, docs/serving.md): the
    ``subgraph_request`` wire op with cross-request micro-batching,
    admission control, and deadline-aware drop.
    """
    return DistServer(dataset, host=host, port=port,
                      dataset_builder=dataset_builder,
                      builder_args=builder_args,
                      num_servers=num_servers, server_rank=server_rank,
                      num_clients=num_clients,
                      max_frame_bytes=max_frame_bytes,
                      reap_interval=reap_interval,
                      fault_plan=fault_plan,
                      enable_metrics=enable_metrics,
                      heartbeat_deadline=heartbeat_deadline,
                      serving=serving)
