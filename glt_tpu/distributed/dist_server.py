"""Sampling server: owns the dataset, produces batches for remote clients.

Rebuild of ``distributed/dist_server.py``: the reference's server owns a
DistDataset plus a pool of mp producers + shm buffers, and clients RPC
``create_sampling_producer / start_new_epoch_sampling /
fetch_one_sampled_message / destroy`` over torch RPC (:38-144).  The TPU
build speaks a small length-prefixed TCP protocol instead (JSON control
frames + TensorMap-serialized sample frames) — the transport the zero-
dependency host runtime actually needs; RDMA-class speed on-host comes from
the shm channel path, and cross-host bulk data rides the same socket.

Protocol (all frames ``u32 kind | u64 len | payload``):
  kind 0: JSON control request/response
  kind 1: serialized SampleMessage
"""
from __future__ import annotations

import json
import queue
import socket
import struct
import threading
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from ..channel.base import bounded_put
from ..channel.serialization import deserialize, serialize

_KIND_JSON = 0
_KIND_MSG = 1


def send_frame(sock: socket.socket, kind: int, payload: bytes) -> None:
    sock.sendall(struct.pack("<IQ", kind, len(payload)) + payload)


def recv_frame(sock: socket.socket):
    hdr = _recv_exact(sock, 12)
    if hdr is None:
        return None, None
    kind, length = struct.unpack("<IQ", hdr)
    data = _recv_exact(sock, length)
    return kind, data


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _Producer:
    """Server-side sampling producer filling a bounded buffer
    (the reference's producer + shm buffer pair, dist_server.py:83-116).

    Two backends, chosen by ``num_workers``:
      * 0 — one in-server thread driving a collocated NeighborLoader;
      * >0 — an :class:`MpSamplingProducer` worker fleet feeding a shm
        ring (the reference's mp producer pool on the server,
        dist_server.py:83-116), drained into the bounded buffer by a
        forwarder thread.  Requires the server's picklable
        ``dataset_builder``.
    """

    def __init__(self, dataset, num_neighbors, input_nodes, batch_size,
                 buffer_capacity: int = 8, seed: int = 0,
                 num_workers: int = 0, dataset_builder=None,
                 builder_args: tuple = (),
                 channel_capacity_bytes: int = 64 * 1024 * 1024):
        self.buffer: "queue.Queue" = queue.Queue(maxsize=buffer_capacity)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._mp_producer = None
        self._channel = None
        if num_workers > 0:
            if dataset_builder is None:
                raise ValueError(
                    "num_workers > 0 needs the server started with a "
                    "picklable dataset_builder (init_server(..., "
                    "dataset_builder=...))")
            from ..channel import ShmChannel
            from .dist_options import MpSamplingWorkerOptions
            from .dist_sampling_producer import MpSamplingProducer

            self._channel = ShmChannel(
                capacity_bytes=channel_capacity_bytes)
            self._mp_producer = MpSamplingProducer(
                dataset_builder, builder_args, list(num_neighbors),
                np.asarray(input_nodes, np.int64), int(batch_size),
                MpSamplingWorkerOptions(num_workers=num_workers),
                self._channel, shuffle=True, seed=seed)
            self._mp_producer.init()
            nbatches = self._mp_producer.num_expected()
        else:
            from ..loader.node_loader import NeighborLoader

            self.loader = NeighborLoader(dataset, num_neighbors,
                                         input_nodes, batch_size=batch_size,
                                         shuffle=True, seed=seed)
            nbatches = len(self.loader)
        self._num_expected = nbatches

    def num_expected(self) -> int:
        return self._num_expected

    def start_epoch(self) -> None:
        if self._thread is not None:
            # Tell the previous epoch's thread to stop before joining: a
            # client that abandoned its epoch mid-way (early stopping)
            # leaves the thread wedged on the bounded buffer, and without
            # the stop signal this join would block 60s and then poison
            # the producer.
            self._stop.set()
            self._thread.join(timeout=60)
            if self._thread.is_alive():
                raise RuntimeError("previous epoch still producing")
        self._stop.clear()
        # Drop anything a previous epoch left behind (in particular a
        # relayed error the client never fetched) so it cannot poison
        # this epoch's first fetch.
        while True:
            try:
                self.buffer.get_nowait()
            except queue.Empty:
                break
        if self._mp_producer is not None:
            self._mp_producer.produce_all()
            self._thread = threading.Thread(target=self._forward_mp,
                                            daemon=True)
        else:
            self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from .sample_message import batch_to_message

        # Loader failures are relayed to the fetching client (same
        # contract as _forward_mp) instead of dying silently here.
        try:
            for batch in self.loader:
                # stop-aware put so a producer whose client vanished
                # mid-epoch exits instead of wedging on the bounded buffer
                # (and permanently poisoning this producer id).
                if not bounded_put(self.buffer,
                                   serialize(batch_to_message(batch)),
                                   self._stop):
                    return
        except Exception as e:  # noqa: BLE001 — relayed to client
            bounded_put(self.buffer, e, self._stop)

    def _forward_mp(self) -> None:
        # iter_messages raises after max_respawns of fruitless worker
        # deaths; relay that to the fetching client instead of discarding
        # it in this daemon thread (which would hang the client forever).
        try:
            for msg in self._mp_producer.iter_messages():
                if not bounded_put(self.buffer, serialize(msg), self._stop):
                    return
        except Exception as e:  # noqa: BLE001 — relayed to client
            bounded_put(self.buffer, e, self._stop)

    def fetch(self) -> bytes:
        item = self.buffer.get()
        if isinstance(item, Exception):
            raise RuntimeError(f"server-side sampling failed: {item}")
        return item

    def stop(self) -> None:
        self._stop.set()
        if self._mp_producer is not None:
            # Order matters: shutdown() first sets the producer's stopping
            # flag so a forwarder blocked in channel.recv exits at its next
            # timeout, THEN the thread is joined, and the shm segment is
            # only unlinked once the forwarder is provably out of recv —
            # closing under its feet would be a native use-after-free.
            self._mp_producer.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=30)
        if self._mp_producer is not None:
            if self._thread is None or not self._thread.is_alive():
                self._channel.close()
            # else: leak the segment rather than unmap it under a live
            # reader; the process exiting reclaims it.


class DistServer:
    """Args mirror init_server (dist_server.py:158-190)."""

    def __init__(self, dataset, host: str = "127.0.0.1", port: int = 0,
                 dataset_builder=None, builder_args: tuple = (),
                 num_servers: int = 1, server_rank: int = 0,
                 num_clients: int = 0):
        from .dist_context import _set_default, make_server_context

        self.dataset = dataset
        self._dataset_builder = dataset_builder
        self._builder_args = builder_args
        # The server's own topology record; installed as the process
        # context only when none exists (several roles can share one
        # process in the single-host test topology — call
        # init_server_context explicitly to claim the global).
        self.context = make_server_context(num_servers, server_rank,
                                           num_clients)
        _set_default(self.context)
        self._producers: Dict[int, _Producer] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.addr = self._sock.getsockname()
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # -- request handlers (cf. _call_func_on_server, dist_server.py:214) ---
    def _handle(self, req: dict):
        op = req["op"]
        if op == "get_dataset_meta":
            g = self.dataset.get_graph()
            return {"num_nodes": g.num_nodes, "num_edges": g.num_edges,
                    "server_rank": self.context.rank,
                    "num_servers": self.context.world_size}
        if op == "create_sampling_producer":
            # Construct outside the lock: mp-producer setup (process spawn
            # + dataset rebuild) can take seconds and must not stall other
            # clients' create/destroy requests.
            prod = _Producer(
                self.dataset, req["num_neighbors"],
                np.asarray(req["input_nodes"], np.int64),
                req["batch_size"],
                buffer_capacity=req.get("buffer_capacity", 8),
                seed=req.get("seed", 0),
                num_workers=req.get("num_workers", 0),
                dataset_builder=self._dataset_builder,
                builder_args=self._builder_args,
                channel_capacity_bytes=req.get(
                    "channel_capacity_bytes", 64 * 1024 * 1024))
            with self._lock:
                pid = self._next_id
                self._next_id += 1
                self._producers[pid] = prod
            return {"producer_id": pid,
                    "num_expected": prod.num_expected()}
        if op == "start_new_epoch_sampling":
            self._producers[req["producer_id"]].start_epoch()
            return {"ok": True}
        if op == "destroy_sampling_producer":
            with self._lock:
                prod = self._producers.pop(req["producer_id"], None)
            if prod is not None:
                prod.stop()
            return {"ok": True}
        if op == "exit":
            self._stop.set()
            return {"ok": True}
        raise ValueError(f"unknown op {op!r}")

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                kind, data = recv_frame(conn)
                if kind is None:
                    return
                req = json.loads(data)
                if req["op"] == "fetch_one_sampled_message":
                    payload = self._producers[req["producer_id"]].fetch()
                    send_frame(conn, _KIND_MSG, payload)
                else:
                    resp = self._handle(req)
                    send_frame(conn, _KIND_JSON, json.dumps(resp).encode())
        except Exception as e:  # connection-scoped errors end the session
            try:
                send_frame(conn, _KIND_JSON,
                           json.dumps({"error": str(e)}).encode())
            except OSError:
                pass
        finally:
            conn.close()

    def wait_for_exit(self, timeout: Optional[float] = None) -> None:
        self._stop.wait(timeout)

    def shutdown(self) -> None:
        self._stop.set()
        # Stop every live producer: with the mp backend each one owns a
        # worker-process fleet and a shm ring that would otherwise outlive
        # the client that forgot to destroy it.
        with self._lock:
            producers = list(self._producers.values())
            self._producers.clear()
        for prod in producers:
            prod.stop()
        try:
            self._sock.close()
        except OSError:
            pass


def init_server(dataset, host: str = "127.0.0.1", port: int = 0,
                dataset_builder=None, builder_args: tuple = (),
                num_servers: int = 1, server_rank: int = 0,
                num_clients: int = 0) -> DistServer:
    """Start a sampling server (cf. init_server, dist_server.py:158-190).

    Pass a picklable ``dataset_builder`` (+``builder_args``) to enable
    mp producer pools for clients requesting
    ``RemoteSamplingWorkerOptions(num_workers > 0)``.
    ``num_servers``/``server_rank``/``num_clients`` record the fleet
    topology in this process's :class:`~.dist_context.DistContext`.
    """
    return DistServer(dataset, host=host, port=port,
                      dataset_builder=dataset_builder,
                      builder_args=builder_args,
                      num_servers=num_servers, server_rank=server_rank,
                      num_clients=num_clients)
