"""Multiprocess sampling producers feeding a shm channel.

Rebuild of ``distributed/dist_sampling_producer.py``: the reference spawns
N sampling subprocesses, each running ``_sampling_worker_loop`` — init RPC,
build a sampler, pull seed slices from an mp task queue, push sampled
messages into the shm channel (:52-260).  TPU differences: workers run the
**CPU JAX backend** (the TPU chip belongs to the trainer process), build
their dataset from a picklable builder (typically mmap-backed .npy loads,
replacing the reference's shared-memory tensor IPC), and ship fully
collated host batches (features gathered worker-side via ``cpu_get``, as
the reference's workers do).  Commands mirror the reference's
``SAMPLE_ALL`` / ``STOP`` protocol.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import threading
from typing import Callable, Optional, Sequence

import numpy as np

from ..channel import ShmChannel
from ..obs import propagate as _prop
from ..obs.trace import auto_trace, auto_trace_export
from ..obs.trace import current as _current_tracer
from ..obs.trace import span as _span
from .dist_options import MpSamplingWorkerOptions
from .sample_message import batch_to_message

_CMD_SAMPLE_EPOCH = 0
_CMD_STOP = 1

_WORKER_KEY = "#worker"
# Worker clock stamp riding each message: [worker pid, send time in the
# worker's trace clock (us)].  Popped by the consumer (_account) and
# turned into an ``obs.clock_oneway`` sync sample — the shm ring has no
# response path, so this one-way direction is what aligns worker clocks
# in `obs merge`.  Only attached while the worker traces.
_OBS_KEY = "#obs"

# Sampler-construction kwargs the worker loop honors for the node kind;
# dist_loader validates mp-mode kwargs against this same set.
WORKER_SAMPLER_KWARGS = frozenset({"frontier_cap", "with_edge",
                                   "last_hop_dedup"})


def _sampling_worker_loop(worker_id, dataset_builder, builder_args,
                          num_neighbors, batch_size, channel, task_queue,
                          seed, kind="node", kind_kwargs=None):
    """Subprocess body (cf. dist_sampling_producer.py:52).

    ``kind`` selects the sampling task, mirroring the reference's three
    concrete distributed loaders (dist_neighbor_loader.py:28,
    dist_link_neighbor_loader.py:31, dist_subgraph_loader.py:28):
      * 'node': chunk entries are seed node ids;
      * 'link': chunk entries are seed-edge POSITIONS into
        ``kind_kwargs['edge_label_index']``;
      * 'subgraph': seed node ids, induced extraction with
        ``kind_kwargs['max_degree']``.
    """
    # The TPU chip belongs to the trainer; workers sample on host CPU.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..loader.node_loader import NodeLoader
    from ..sampler.base import EdgeSamplerInput, NodeSamplerInput
    from ..sampler.neighbor_sampler import NeighborSampler
    from .sample_message import hetero_batch_to_message

    kk = kind_kwargs or {}
    data = dataset_builder(*builder_args)
    if kind == "hetero_node":
        from ..loader.hetero_neighbor_loader import HeteroNeighborLoader

        input_type = kk["input_type"]
        collate_loader = HeteroNeighborLoader(
            data, num_neighbors, (input_type, np.empty(0, np.int64)),
            batch_size=batch_size, frontier_cap=kk.get("frontier_cap"),
            seed=seed + worker_id,
            last_hop_dedup=kk.get("last_hop_dedup", True))
        sampler = collate_loader.sampler
    else:
        sampler = NeighborSampler(data.get_graph(), num_neighbors,
                                  batch_size=batch_size,
                                  frontier_cap=kk.get("frontier_cap"),
                                  with_edge=kk.get("with_edge", True),
                                  seed=seed + worker_id,
                                  last_hop_dedup=kk.get("last_hop_dedup",
                                                        True))
        collate_loader = NodeLoader(data, sampler, np.empty(0, np.int64),
                                    batch_size=batch_size)

    # Link chunks arrive as (edge_label_index[2, n], labels-or-None) slices
    # shipped in the task payload; node/subgraph chunks are id arrays.
    def chunk_len(payload):
        if kind == "link":
            return payload[0].shape[1]
        return payload.shape[0]

    def sample(payload, lo, hi):
        if kind == "node":
            return sampler.sample_from_nodes(
                NodeSamplerInput(payload[lo:hi]))
        if kind == "hetero_node":
            return sampler.sample_from_nodes(
                NodeSamplerInput(payload[lo:hi], kk["input_type"]))
        if kind == "link":
            eli_c, lab_c = payload
            return sampler.sample_from_edges(EdgeSamplerInput(
                row=eli_c[0, lo:hi], col=eli_c[1, lo:hi],
                label=None if lab_c is None else lab_c[lo:hi],
                neg_sampling=kk.get("neg_sampling")))
        if kind == "subgraph":
            return sampler.subgraph(NodeSamplerInput(payload[lo:hi]),
                                    max_degree=kk["max_degree"])
        raise ValueError(f"unknown sampling kind {kind!r}")

    # GLT_OBS_TRACE_DIR: the worker writes its own per-process trace
    # file, exported when the parent sends _CMD_STOP.
    trace_path = auto_trace(f"worker{worker_id}")

    while True:
        # Idle worker awaiting commands: there is no liveness to probe
        # from here (the parent owns it), and shutdown() sends _CMD_STOP
        # then terminates stragglers — the wait is bounded by the parent.
        # gltlint: disable-next=unbounded-blocking-get
        task = task_queue.get()
        cmd, payload, meta = (task if len(task) == 3
                              else (task[0], task[1], None))
        if cmd == _CMD_STOP:
            auto_trace_export(trace_path)
            break
        ctx = meta or {}
        n = chunk_len(payload)
        for lo in range(0, n, batch_size):
            hi = min(lo + batch_size, n)
            with _span("worker.sample_batch", worker=worker_id,
                       lo=lo) as sp:
                sp.link(ctx.get("tid"), ctx.get("sid"))
                out = sample(payload, lo, hi)
                batch = collate_loader._collate_fn(out, hi - lo)
                if kind == "hetero_node":
                    msg = hetero_batch_to_message(batch)
                else:
                    msg = batch_to_message(batch)
            # Provenance tag so the trainer can attribute delivered batches
            # per worker and reissue a dead worker's unfinished seed range.
            msg[_WORKER_KEY] = np.array([worker_id], np.int64)
            tracer = _current_tracer()
            if tracer is not None:
                msg[_OBS_KEY] = np.array(
                    [float(os.getpid()), tracer.now_us()], np.float64)
            channel.send(msg)


class MpSamplingProducer:
    """Spawn + drive sampling workers (cf. DistMpSamplingProducer).

    Args:
      dataset_builder: picklable top-level callable rebuilding the Dataset
        inside each worker (e.g. mmap .npy loads).
      input_nodes: global seed ids for this loader.
    """

    def __init__(
        self,
        dataset_builder: Callable,
        builder_args: tuple,
        num_neighbors: Sequence[int],
        input_nodes: np.ndarray,
        batch_size: int,
        options: MpSamplingWorkerOptions,
        channel: ShmChannel,
        shuffle: bool = False,
        kind: str = "node",
        kind_kwargs: Optional[dict] = None,
        seed: int = 0,
    ):
        self.kind = kind
        # The seed-edge arrays stay host-side in the producer; workers get
        # per-chunk slices in their task payload (shipping the full array
        # to every spawned worker would copy it num_workers times).
        self.kind_kwargs = dict(kind_kwargs or {})
        self._link_eli = self.kind_kwargs.pop("edge_label_index", None)
        self._link_label = self.kind_kwargs.pop("edge_label", None)
        self.input_nodes = np.asarray(input_nodes).astype(np.int64)
        self.batch_size = int(batch_size)
        self.options = options
        self.channel = channel
        self.shuffle = shuffle
        # Loader seed + options.worker_seed both feed the stream so mp mode
        # honors per-loader seeding the way collocated mode does.
        self._base_seed = int(options.worker_seed) + int(seed)
        self._rng = np.random.default_rng(self._base_seed)
        self._ctx = mp.get_context("spawn")
        self._task_queues = []
        self._workers = []
        self._chunks = []
        self._delivered = []
        self._builder = (dataset_builder, builder_args, list(num_neighbors))
        self._epoch_trace_ctx: Optional[dict] = None
        self.max_respawns = 3
        # Cooperative stop for consumers blocked in iter_messages (e.g. a
        # server forwarder thread): set before shutdown() so the iterator
        # exits instead of treating the stopped workers as crashed.
        self._stopping = threading.Event()

    def _spawn(self, w: int):
        builder, args, nn = self._builder
        tq = self._ctx.Queue()
        p = self._ctx.Process(
            target=_sampling_worker_loop,
            args=(w, builder, args, nn, self.batch_size, self.channel,
                  tq, self._base_seed, self.kind,
                  self.kind_kwargs),
            daemon=True)
        p.start()
        return p, tq

    def init(self) -> None:
        for w in range(self.options.num_workers):
            p, tq = self._spawn(w)
            self._task_queues.append(tq)
            self._workers.append(p)

    def _respawn(self, w: int) -> None:
        p, tq = self._spawn(w)
        self._workers[w] = p
        self._task_queues[w] = tq

    def _ensure_alive(self) -> None:
        """Restart dead workers (failure handling the reference lacks,
        SURVEY §5: its mp workers die silently and the epoch hangs)."""
        for w, p in enumerate(self._workers):
            if not p.is_alive():
                self._respawn(w)

    def num_expected(self) -> int:
        n = self.input_nodes.shape[0]
        return (n + self.batch_size - 1) // self.batch_size

    def _payload(self, chunk: np.ndarray):
        """Task payload for a seed chunk: the ids themselves, or for the
        link kind the sliced seed-edge endpoints/labels (``chunk`` holds
        positions into the producer-held ``edge_label_index``)."""
        if self.kind == "link":
            lab = (None if self._link_label is None
                   else self._link_label[chunk])
            return (self._link_eli[:, chunk], lab)
        return chunk

    def produce_all(self, trace_ctx: Optional[dict] = None) -> None:
        """Kick one epoch: split seeds batch-aligned across workers
        (cf. dist_sampling_producer.py:229-247).

        ``trace_ctx`` (the epoch's wire trace context) rides the task
        payload so worker-side sampling spans join the epoch's trace.
        """
        self._ensure_alive()
        self._epoch_trace_ctx = trace_ctx
        ids = self.input_nodes
        if self.shuffle:
            ids = ids[self._rng.permutation(ids.shape[0])]
        k = max(1, len(self._workers))
        batches_per_worker = (self.num_expected() + k - 1) // k
        span = batches_per_worker * self.batch_size
        self._chunks = []
        self._delivered = []
        for w, tq in enumerate(self._task_queues):
            chunk = ids[w * span: (w + 1) * span]
            self._chunks.append(chunk)
            self._delivered.append(0)
            if chunk.shape[0] > 0:
                tq.put((_CMD_SAMPLE_EPOCH, self._payload(chunk),
                        trace_ctx))

    def iter_messages(self):
        """Yield every message of the current epoch, surviving mid-epoch
        worker death.

        The reference's known gap (SURVEY §5): a dead mp worker's batches
        never arrive and the trainer blocks forever on channel recv.  Here
        recv has a heartbeat timeout; on timeout, dead workers are found,
        the channel is drained of their in-flight batches (the shm ring
        outlives the producer process, so nothing sent is lost), and each
        dead worker is respawned with its undelivered batch-aligned seed
        remainder.  Every batch of the epoch is yielded exactly once.
        """
        total = self.num_expected()
        got = 0
        fruitless_respawns = 0
        while got < total:
            if self._stopping.is_set():
                return
            msg = self.channel.recv(timeout=self.options.heartbeat_secs)
            if msg is not None:
                self._account(msg)
                got += 1
                fruitless_respawns = 0
                yield msg
                continue
            dead = [w for w, p in enumerate(self._workers)
                    if not p.is_alive()]
            if not dead:
                continue  # slow batch, keep waiting
            # Drain in-flight messages before computing remainders: a batch
            # already in the ring must not be reissued.
            while True:
                m = self.channel.recv(timeout=0)
                if m is None:
                    break
                self._account(m)
                got += 1
                yield m
            # Deterministic failures (bad builder, import error) would
            # otherwise respawn forever; give up once respawns stop
            # yielding any progress.
            fruitless_respawns += 1
            if fruitless_respawns > self.max_respawns:
                raise RuntimeError(
                    f"sampling workers died {fruitless_respawns} times "
                    f"without delivering a batch; giving up (check the "
                    f"dataset_builder runs in a spawned subprocess)")
            for w in dead:
                rest = self._chunks[w][
                    self._delivered[w] * self.batch_size:]
                self._respawn(w)
                self._chunks[w] = rest
                self._delivered[w] = 0
                if rest.shape[0] > 0:
                    self._task_queues[w].put(
                        (_CMD_SAMPLE_EPOCH, self._payload(rest),
                         self._epoch_trace_ctx))

    def _account(self, msg) -> None:
        tag = msg.pop(_WORKER_KEY, None)
        if tag is not None:
            self._delivered[int(np.asarray(tag).ravel()[0])] += 1
        stamp = msg.pop(_OBS_KEY, None)
        if stamp is not None:
            arr = np.asarray(stamp).ravel()
            if arr.shape[0] >= 2:
                _prop.record_clock_oneway(int(arr[0]), "worker",
                                          float(arr[1]))

    def shutdown(self) -> None:
        self._stopping.set()
        for tq in self._task_queues:
            try:
                tq.put((_CMD_STOP, None, None))
            except Exception:
                pass
        for p in self._workers:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._workers.clear()
        self._task_queues.clear()
