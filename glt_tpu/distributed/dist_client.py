"""Client side of the server–client deployment.

Rebuild of ``distributed/dist_client.py`` + the pull-based
``RemoteReceivingChannel`` (channel/remote_channel.py:24-100): the client
asks the server to create a producer, kicks epochs, and prefetches sampled
messages over the socket with a configurable depth (default 4, matching
RemoteDistSamplingWorkerOptions, dist_options.py:202-254).
"""
from __future__ import annotations

import dataclasses
import json
import queue
import socket
import threading
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from ..channel.base import bounded_put
from ..channel.serialization import deserialize
from ..loader.transform import Batch
from .dist_server import _KIND_JSON, _KIND_MSG, recv_frame, send_frame
from .sample_message import message_to_batch


class RemoteServerConnection:
    def __init__(self, addr: Tuple[str, int],
                 timeout: Optional[float] = 600.0):
        # Bounded waits so a dead server surfaces as an error instead of a
        # hang (the reference's RPC timeouts, dist_options.py rpc_timeout).
        self.sock = socket.create_connection(addr, timeout=timeout)
        self.sock.settimeout(timeout)
        self._lock = threading.Lock()
        # A timeout/short-read mid-exchange leaves an unconsumed response
        # in flight: the framed protocol is desynced and every later
        # exchange would misparse.  Poison the connection instead.
        self._broken = False

    def _exchange(self, payload: bytes):
        with self._lock:
            if self._broken:
                raise RuntimeError("connection poisoned by an earlier "
                                   "timeout/protocol error; reconnect")
            try:
                send_frame(self.sock, _KIND_JSON, payload)
                kind, data = recv_frame(self.sock)
            except Exception:
                self._broken = True
                raise
            if kind is None or data is None:
                # EOF (clean or mid-frame) — the server closed the socket
                # (e.g. died or dropped us after an error frame).
                self._broken = True
                raise RuntimeError("server closed the connection")
            return kind, data

    def request(self, **req) -> dict:
        kind, data = self._exchange(json.dumps(req).encode())
        if kind != _KIND_JSON:
            raise RuntimeError("expected JSON response")
        resp = json.loads(data)
        if "error" in resp:
            raise RuntimeError(f"server error: {resp['error']}")
        return resp

    def fetch_message(self, producer_id: int):
        kind, data = self._exchange(json.dumps(
            {"op": "fetch_one_sampled_message",
             "producer_id": producer_id}).encode())
        if kind != _KIND_MSG:
            raise RuntimeError(
                json.loads(data).get("error", "bad frame"))
        return deserialize(memoryview(data))

    @property
    def broken(self) -> bool:
        return self._broken

    def close(self) -> None:
        self.sock.close()


class RemoteNeighborLoader:
    """Loader iterating batches produced on a remote sampling server
    (the reference's DistLoader 'remote' mode, dist_loader.py:188-217)."""

    def __init__(
        self,
        server_addr: Tuple[str, int],
        num_neighbors: Sequence[int],
        input_nodes: np.ndarray,
        batch_size: int = 512,
        prefetch: Optional[int] = None,
        seed: int = 0,
        worker_options=None,
    ):
        from .dist_options import RemoteSamplingWorkerOptions

        opts = worker_options or RemoteSamplingWorkerOptions()
        if not isinstance(opts, RemoteSamplingWorkerOptions):
            raise TypeError(
                f"worker_options must be RemoteSamplingWorkerOptions, got "
                f"{type(opts).__name__}")
        # An explicit ``prefetch`` argument wins over the options default.
        if prefetch is not None:
            opts = dataclasses.replace(opts, prefetch_size=int(prefetch))
        self.conn = RemoteServerConnection(server_addr,
                                           timeout=float(opts.rpc_timeout))
        resp = self.conn.request(
            op="create_sampling_producer",
            num_neighbors=list(num_neighbors),
            input_nodes=np.asarray(input_nodes).tolist(),
            batch_size=int(batch_size),
            seed=seed + opts.worker_seed,
            num_workers=int(opts.num_workers),
            buffer_capacity=int(opts.buffer_capacity),
            channel_capacity_bytes=int(opts.channel_capacity_bytes))
        self.producer_id = resp["producer_id"]
        self.num_expected = resp["num_expected"]
        self.prefetch = max(1, int(opts.prefetch_size))

    def __len__(self) -> int:
        return self.num_expected

    def __iter__(self) -> Iterator[Batch]:
        self.conn.request(op="start_new_epoch_sampling",
                          producer_id=self.producer_id)
        # Bounded to the configured prefetch depth: a slow trainer holds at
        # most ``prefetch`` unconsumed messages instead of buffering the
        # whole epoch in client RAM (the reference's prefetch_size
        # semantics, channel/remote_channel.py:24-85).
        buf: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def prefetcher():
            # A fetch error (dead server, socket timeout) is forwarded to
            # the consumer instead of dying silently in this thread and
            # leaving the consumer blocked forever on buf.get().
            try:
                for _ in range(self.num_expected):
                    msg = self.conn.fetch_message(self.producer_id)
                    if not bounded_put(buf, msg, stop):
                        return
            except Exception as e:  # noqa: BLE001 — relayed to consumer
                bounded_put(buf, e, stop)

        t = threading.Thread(target=prefetcher, daemon=True)
        t.start()
        try:
            for _ in range(self.num_expected):
                item = buf.get()
                if isinstance(item, Exception):
                    raise RuntimeError(
                        f"remote sampling prefetch failed: {item}") from item
                yield message_to_batch(item)
        finally:
            stop.set()

    def shutdown(self, exit_server: bool = False) -> None:
        try:
            if not self.conn.broken:
                self.conn.request(op="destroy_sampling_producer",
                                  producer_id=self.producer_id)
                if exit_server:
                    self.conn.request(op="exit")
        finally:
            self.conn.close()
