"""Client side of the server–client deployment.

Rebuild of ``distributed/dist_client.py`` + the pull-based
``RemoteReceivingChannel`` (channel/remote_channel.py:24-100): the client
asks the server to create a producer, kicks epochs, and prefetches sampled
messages over the socket with a configurable depth (default 4, matching
RemoteDistSamplingWorkerOptions, dist_options.py:202-254).

Fault tolerance: a :class:`RemoteServerConnection` is never terminally
poisoned — retryable failures (timeout, ECONNRESET, EOF, a desynced
frame) reconnect with exponential backoff + jitter, optionally failing
over across replica addresses, and the sequenced fetch protocol
(``seq``/``ack``, dist_server.py) re-delivers exactly the batches lost in
flight, with duplicate suppression here.  Every batch of an epoch is
delivered exactly once across arbitrarily many reconnects.
"""
from __future__ import annotations

import dataclasses
import json
import queue
import random
import socket
import struct
import threading
import time
import uuid
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from ..channel.base import QueueSourceDied, bounded_get, bounded_put
from ..channel.serialization import deserialize
from ..loader.transform import Batch
from ..obs import flight as _flight
from ..obs import metrics as _metrics
from ..obs import propagate as _prop
from ..obs.trace import auto_trace, auto_trace_export
from ..obs.trace import current as _current_tracer
from ..obs.trace import span as _span
from .dist_server import (
    _KIND_JSON,
    _KIND_MSG,
    DEFAULT_MAX_FRAME_BYTES,
    ProtocolError,
    recv_frame,
    send_frame,
)
from .sample_message import message_to_batch


# Remote-loader metrics (docs/observability.md "glt.remote.*"): the
# canonical cross-epoch view of the sequence-number accounting that
# ``epoch_stats`` snapshots per epoch.
_M_RECEIVED = _metrics.counter(
    "glt.remote.batches_received", "unique sampled messages received")
_M_DUPLICATES = _metrics.counter(
    "glt.remote.duplicates", "replayed messages suppressed client-side")
_M_RECONNECTS = _metrics.counter(
    "glt.remote.reconnects", "socket reconnects (backoff/failover)")
_M_EPOCHS = _metrics.counter(
    "glt.remote.epochs", "remote sampling epochs completed")


def publish_epoch_stats(stats: dict) -> dict:
    """Fold one epoch's seq accounting into the ``glt.remote.*`` counters.

    The unified read for what ``RemoteNeighborLoader.epoch_stats``
    exposes per epoch (that attribute remains as a back-compat alias —
    the chaos suite asserts exactly-once delivery from it).  Returns
    ``stats`` unchanged.
    """
    _M_RECEIVED.inc(stats.get("received", 0))
    _M_DUPLICATES.inc(stats.get("duplicates", 0))
    _M_RECONNECTS.inc(stats.get("reconnects", 0))
    _M_EPOCHS.inc()
    return stats


class UnknownProducerError(RuntimeError):
    """The server does not know this producer id: its lease expired and
    the reaper GC'd it, it was destroyed, or the connection failed over
    to a replica that never owned it.  The epoch cannot resume — recreate
    the producer (or the loader) to continue."""


# Server codes with no typed client-side exception that the client
# classifies as FATAL for the request at hand: the server spoke clearly
# (wrong epoch, a dead/unknown producer's sampling, a wedged pipeline),
# so retrying or failing over the same request cannot help.  Keeping the
# set explicit — instead of letting unknown codes fall through to the
# same generic error — is what lets gltlint GLT025 prove every code the
# server constructs has a client-side classification.
FATAL_CODES = frozenset({
    "epoch_busy",        # previous epoch still producing (caller bug)
    "stale_epoch",       # request from a superseded epoch
    "sampling_failed",   # server-side sampling raised
    "producer_dead",     # producer thread/process died mid-epoch
    "fatal",             # conn-level terminal server error
})


class RemoteServerConnection:
    """One logical connection to a sampling server (with failover).

    Retryable transport failures trigger reconnect with exponential
    backoff + deterministic jitter, capped by ``max_retries`` /
    ``backoff_base`` / ``backoff_cap``; ``fallback_addrs`` extends the
    connect rotation across replicas.  Structured server errors
    (``{"error":..., "code":...}``) are NOT retried — they are the
    server speaking clearly, e.g. :class:`UnknownProducerError` for a
    GC'd lease.
    """

    RETRYABLE = (OSError, EOFError, ProtocolError)

    def __init__(self, addr: Tuple[str, int],
                 timeout: Optional[float] = 600.0,
                 max_retries: int = 3,
                 backoff_base: float = 0.05,
                 backoff_cap: float = 2.0,
                 fallback_addrs: Sequence[Tuple[str, int]] = (),
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 fault_plan=None,
                 seed: int = 0):
        # Bounded waits so a dead server surfaces as an error instead of a
        # hang (the reference's RPC timeouts, dist_options.py rpc_timeout).
        self.timeout = timeout
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.max_frame_bytes = int(max_frame_bytes)
        self._addrs = [tuple(addr)] + [tuple(a) for a in fallback_addrs]
        self._addr_i = 0
        self._fault_plan = fault_plan
        # Seeded jitter: reconnect storms decorrelate across clients
        # (seed with the client rank) while staying reproducible in tests.
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.sock = None
        self._broken = True          # no socket yet
        self.reconnects = 0          # successful re-connections (stats)
        # Wire context of the epoch in flight (set by the loader): links
        # request/fetch spans and reconnect/replay events to one trace.
        self.epoch_ctx: Optional[dict] = None
        self._connect()

    # -- connection management --------------------------------------------
    def _connect(self) -> None:
        """Connect to the first reachable address, starting at the one
        that last worked (failover rotates only past dead hosts)."""
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
            self._replacing = True
        last_exc = None
        for k in range(len(self._addrs)):
            i = (self._addr_i + k) % len(self._addrs)
            try:
                sock = socket.create_connection(self._addrs[i],
                                                timeout=self.timeout)
            except OSError as e:
                last_exc = e
                continue
            sock.settimeout(self.timeout)
            if self._fault_plan is not None:
                sock = self._fault_plan.wrap(sock)
            if getattr(self, "_replacing", False):
                self.reconnects += 1
                self._replacing = False
                _flight.record("remote.reconnect",
                               addr=list(self._addrs[i]),
                               reconnects=self.reconnects)
                tracer = _current_tracer()
                if tracer is not None:
                    # Tagged with the originating epoch's trace id so a
                    # merged trace attributes reconnect storms to the
                    # batch stream that suffered them.
                    ctx = self.epoch_ctx or {}
                    tracer.instant("remote.reconnect",
                                   trace_id=ctx.get("tid"),
                                   addr=list(self._addrs[i]))
            self.sock = sock
            self._addr_i = i
            self._broken = False
            return
        raise ConnectionError(
            f"could not connect to any of {self._addrs}: {last_exc}")

    def _sleep_backoff(self, attempt: int,
                       stop: Optional[threading.Event]) -> None:
        delay = min(self.backoff_cap, self.backoff_base * (2 ** attempt))
        delay *= 0.5 + 0.5 * self._rng.random()     # jitter
        if stop is not None:
            stop.wait(delay)
        else:
            time.sleep(delay)

    def _exchange(self, payload: bytes,
                  stop: Optional[threading.Event] = None,
                  retries: Optional[int] = None,
                  timeout: Optional[float] = None):
        """One framed round trip; ``timeout`` is the PER-OP socket
        timeout — latency-sensitive ops (serving ``subgraph_request``)
        bound their wait tighter than the connection's ``rpc_timeout``
        default without touching training-path fetches.  Applied per
        attempt and restored afterwards, so the next op on this
        connection sees the default again."""
        retries = self.max_retries if retries is None else int(retries)
        with self._lock:
            last_exc = None
            for attempt in range(retries + 1):
                # Stop-aware: a shutdown mid-retry surfaces immediately
                # instead of sleeping out the backoff schedule.
                if stop is not None and stop.is_set():
                    raise ConnectionAbortedError(
                        "exchange stopped by shutdown")
                if attempt:
                    # Justified (gltlint GLT009): the whole retry loop —
                    # backoff sleep, reconnect, send, recv — deliberately
                    # runs under the per-connection lock.  The framed
                    # protocol is a strict request-response stream: a
                    # second thread interleaving mid-round-trip would
                    # desync the framing for both.  The bounded escape
                    # hatch is interrupt(): it closes the socket out of
                    # band, the blocked I/O raises, and the stop-aware
                    # loop observes `stop` and releases the lock (used by
                    # RemoteNeighborLoader.__iter__'s finally).
                    # gltlint: disable-next=blocking-call-while-holding-lock
                    self._sleep_backoff(attempt - 1, stop)
                    if stop is not None and stop.is_set():
                        raise ConnectionAbortedError(
                            "exchange stopped by shutdown")
                try:
                    if self._broken or self.sock is None:
                        # A timeout/short-read mid-exchange leaves the
                        # framed stream desynced; reconnecting is the only
                        # way to resync it.
                        self._connect()
                    if timeout is not None:
                        self.sock.settimeout(float(timeout))
                    # NTP sample half: t0 just before send, t3 just after
                    # a complete receive, both in the trace clock (only
                    # stamped while tracing — zero timestamp calls when
                    # off).
                    tracer = _current_tracer()
                    t0 = tracer.now_us() if tracer is not None else None
                    send_frame(self.sock, _KIND_JSON, payload)
                    kind, data = recv_frame(
                        self.sock, max_len=self.max_frame_bytes)
                    t3 = tracer.now_us() if tracer is not None else None
                    if kind is None:
                        # EOF (clean or mid-frame) — the server closed the
                        # socket (died, or dropped us after an error).
                        raise ConnectionResetError(
                            "server closed the connection")
                    if kind == _KIND_JSON and b'"error"' in data[:64]:
                        resp = json.loads(data)
                        if resp.get("code") == "protocol":
                            # The server saw a desynced/corrupt frame from
                            # us and is closing: retryable — a fresh
                            # connection resyncs the framing.
                            raise ProtocolError(resp.get("error", ""))
                    if timeout is not None:
                        # Restore the connection-wide default: later ops
                        # on this socket get rpc_timeout semantics back.
                        # (Failure paths mark the socket broken, and the
                        # reconnect re-applies the default.)
                        self.sock.settimeout(self.timeout)
                    return kind, data, t0, t3
                except self.RETRYABLE as e:
                    self._broken = True
                    last_exc = e
            raise RuntimeError(
                f"exchange failed after {retries} retries over "
                f"{self._addrs}: {last_exc}") from last_exc

    @staticmethod
    def _raise_structured(resp: dict) -> None:
        code = resp.get("code")
        if code == "unknown_producer":
            raise UnknownProducerError(resp["error"])
        if code is not None:
            # Serving rejections round-trip as their typed exceptions
            # (Overloaded keeps its retry_after_ms hint).  Local import:
            # training-only deployments never touch glt_tpu.serving.
            from ..serving.errors import SERVING_CODES, error_from_response

            if code in SERVING_CODES:
                raise error_from_response(resp)
            if code in FATAL_CODES:
                # The server's explicit non-retryable verdict: surface
                # the code so operators (and the failover discipline)
                # can tell it from a transport fault.
                raise RuntimeError(
                    f"server error [{code}]: {resp['error']}")
        raise RuntimeError(f"server error: {resp['error']}")

    # -- protocol ----------------------------------------------------------
    def request(self, _stop: Optional[threading.Event] = None,
                _retries: Optional[int] = None,
                _trace_ctx: Optional[dict] = None,
                _timeout: Optional[float] = None, **req) -> dict:
        with _span("remote.request", op=str(req.get("op"))) as sp:
            if self.epoch_ctx:
                sp.link(self.epoch_ctx.get("tid"),
                        self.epoch_ctx.get("sid"))
            if _trace_ctx is not None:
                # Explicit remote parent (the loader passes the EPOCH
                # span for start_new_epoch_sampling: producer spans live
                # far longer than this request's round trip, so they
                # must hang off the epoch, not off this request span).
                req[_prop.WIRE_KEY] = _trace_ctx
            else:
                _prop.inject(req, sp)
            kind, data, t0, t3 = self._exchange(
                json.dumps(req).encode(), stop=_stop, retries=_retries,
                timeout=_timeout)
            if kind != _KIND_JSON:
                raise RuntimeError("expected JSON response")
            resp = json.loads(data)
            _prop.record_clock_sync(resp.pop(_prop.WIRE_KEY, None), t0, t3)
            if "error" in resp:
                self._raise_structured(resp)
            return resp

    def fetch_message(self, producer_id: int, epoch: int = 0,
                      ack: int = -1,
                      stop: Optional[threading.Event] = None):
        """Fetch one sampled message; returns ``(seq, message)``.

        ``ack`` (highest seq contiguously received) releases the server's
        replay window and directs resume after a reconnect.
        """
        with _span("remote.fetch", epoch=epoch) as sp:
            if self.epoch_ctx:
                sp.link(self.epoch_ctx.get("tid"),
                        self.epoch_ctx.get("sid"))
            req = {"op": "fetch_one_sampled_message",
                   "producer_id": producer_id,
                   "epoch": epoch, "ack": ack}
            _prop.inject(req, sp)
            kind, data, t0, t3 = self._exchange(
                json.dumps(req).encode(), stop=stop)
            if kind != _KIND_MSG:
                resp = json.loads(data)
                if "error" in resp:
                    self._raise_structured(resp)
                raise RuntimeError("bad frame")
            # A traced server appends an append-only trailer (clock echo)
            # AFTER the payload — but only when THIS request carried the
            # trace context (negotiation).  Only look for it then, so an
            # untraced exchange can never misread payload bytes that
            # happen to end in the magic.
            if _prop.WIRE_KEY in req:
                payload, echo = _prop.split_trailer(data)
                _prop.record_clock_sync(echo, t0, t3)
            else:
                payload = memoryview(data)
            seq = struct.unpack_from("<Q", payload, 0)[0]
            sp.set(seq=int(seq))
            return int(seq), deserialize(payload[8:])

    def flight_dump(self, retries: int = 0) -> Optional[dict]:
        """Pull the server's flight-recorder ring (``flight_dump`` op).

        Returns the dump object (``glt_flight`` schema,
        :func:`glt_tpu.obs.flight.validate_flight_dump`), or **None
        against a pre-flight-recorder server** — an old server answers
        the unknown op with its fatal error and closes the connection,
        which this helper degrades to "no black box available"
        (mixed-version contract; the connection reconnects on next
        use).  Transport failures degrade the same way: this is a
        best-effort postmortem read, never a new failure mode.
        """
        try:
            resp = self.request(op="flight_dump", _retries=int(retries))
        except (RuntimeError, OSError):
            self._broken = True       # old server closed after the error
            return None
        flight = resp.get("flight")
        return flight if isinstance(flight, dict) else None

    def profile_capture(self, dir: Optional[str] = None,
                        millis: float = 50.0,
                        retries: int = 0) -> Optional[dict]:
        """Trigger a bounded profiler capture on the server host
        (``profile_capture`` op, docs/observability.md "Triggered
        profiling").

        Returns ``{"ok", "dir", "millis"}`` naming the server-side
        capture directory, or **None against a pre-14 server** — the
        unknown-op fatal error (and any transport failure) degrades to
        "no capture available", never a new failure mode; the
        connection reconnects on next use.
        """
        req: dict = {"op": "profile_capture", "millis": float(millis),
                     "_retries": int(retries)}
        if dir is not None:
            req["dir"] = str(dir)
        try:
            resp = self.request(**req)
        except (RuntimeError, OSError):
            self._broken = True       # old server closed after the error
            return None
        return resp if isinstance(resp, dict) and resp.get("ok") else None

    @property
    def broken(self) -> bool:
        return self._broken

    def interrupt(self) -> None:
        """Force-close the socket so a thread blocked inside an exchange
        raises promptly (and observes its stop event instead of
        retrying).  The connection transparently reconnects on next use."""
        self._broken = True
        try:
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass

    def close(self) -> None:
        try:
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass


class RemoteNeighborLoader:
    """Loader iterating batches produced on a remote sampling server
    (the reference's DistLoader 'remote' mode, dist_loader.py:188-217).

    After each epoch, ``epoch_stats`` records the sequence-number
    accounting: ``{"received", "duplicates", "reconnects", "seqs"}`` —
    the chaos suite asserts exactly-once delivery from it.  The same
    numbers also fold into the unified ``glt.remote.*`` counters
    (:func:`publish_epoch_stats`); prefer reading those —
    ``epoch_stats`` is kept as a back-compat alias.
    """

    def __init__(
        self,
        server_addr: Tuple[str, int],
        num_neighbors: Sequence[int],
        input_nodes: np.ndarray,
        batch_size: int = 512,
        prefetch: Optional[int] = None,
        seed: int = 0,
        worker_options=None,
        fault_plan=None,
    ):
        from .dist_options import RemoteSamplingWorkerOptions

        opts = worker_options or RemoteSamplingWorkerOptions()
        if not isinstance(opts, RemoteSamplingWorkerOptions):
            raise TypeError(
                f"worker_options must be RemoteSamplingWorkerOptions, got "
                f"{type(opts).__name__}")
        # An explicit ``prefetch`` argument wins over the options default.
        if prefetch is not None:
            opts = dataclasses.replace(opts, prefetch_size=int(prefetch))
        self.conn = RemoteServerConnection(
            server_addr,
            timeout=float(opts.rpc_timeout),
            max_retries=int(opts.max_retries),
            backoff_base=float(opts.backoff_base),
            backoff_cap=float(opts.backoff_cap),
            fallback_addrs=tuple(opts.fallback_addrs),
            max_frame_bytes=int(opts.max_frame_bytes),
            fault_plan=fault_plan,
            seed=seed)
        # Stable per-loader identity: a re-create after lease GC (or a
        # retried create whose response was lost) tears down the previous
        # producer server-side instead of leaking it.
        self._client_key = uuid.uuid4().hex
        resp = self.conn.request(
            op="create_sampling_producer",
            num_neighbors=list(num_neighbors),
            input_nodes=np.asarray(input_nodes).tolist(),
            batch_size=int(batch_size),
            seed=seed + opts.worker_seed,
            num_workers=int(opts.num_workers),
            buffer_capacity=int(opts.buffer_capacity),
            channel_capacity_bytes=int(opts.channel_capacity_bytes),
            lease_secs=float(opts.lease_secs),
            replay_window=int(opts.replay_window),
            client_key=self._client_key)
        self.producer_id = resp["producer_id"]
        self.num_expected = resp["num_expected"]
        self.prefetch = max(1, int(opts.prefetch_size))
        self._epoch = 0
        self.epoch_stats: dict = {}
        # GLT_OBS_TRACE_DIR: per-process trace file exported at shutdown
        # (one track per fleet process; stitch with `obs merge`).
        self._trace_export_path = auto_trace("client")

    def __len__(self) -> int:
        return self.num_expected

    # -- state-capture protocol (glt_tpu.ckpt) -----------------------------
    def state_dict(self) -> dict:
        """Per-producer epoch-fence + accounting state for checkpoints.

        The durable facts a restarted client needs: its epoch fence (so
        the resumed process's next epoch outranks every message the
        killed process's epoch could still replay — the server discards
        stale-epoch fetches), its ``client_key`` (a re-created producer
        under the same key tears down the orphan server-side), and the
        last completed epoch's seq accounting for the record.
        """
        return {
            "epoch": int(self._epoch),
            "client_key": self._client_key,
            "num_expected": int(self.num_expected),
            "last_epoch_stats": {
                k: sorted(v) if isinstance(v, set) else v
                for k, v in self.epoch_stats.items()},
        }

    def load_state_dict(self, state: dict) -> None:
        """Resume the epoch fence in THIS (freshly constructed) loader.

        The fence only ratchets forward: a fresh loader starts at 0, so
        ``max`` keeps the restored fence above anything the interrupted
        run produced — its next ``__iter__`` starts epoch ``saved + 1``
        and the server's epoch check discards any in-flight replays of
        the killed epoch (PR-4 fencing, composing with PR-4 replay).
        """
        saved = int(state["epoch"])
        if saved != self._epoch and self.num_expected != int(
                state.get("num_expected", self.num_expected)):
            raise ValueError(
                f"checkpoint was taken against a producer expecting "
                f"{state.get('num_expected')} batches; this loader "
                f"expects {self.num_expected} — different seed set?")
        self._epoch = max(self._epoch, saved)

    def __iter__(self) -> Iterator[Batch]:
        self._epoch += 1
        epoch = self._epoch
        with _span("remote.epoch", epoch=epoch) as ep_span:
            yield from self._iter_epoch(epoch, ep_span)

    def _iter_epoch(self, epoch: int, ep_span) -> Iterator[Batch]:
        # The epoch span is the trace ROOT: every request/fetch span
        # (this process), server stage span, and producer/worker span of
        # this epoch joins its trace id — one causally-linked tree per
        # remote-sampling run once `obs merge` aligns the clocks.
        self.conn.epoch_ctx = ep_span.context()
        self.conn.request(op="start_new_epoch_sampling",
                          producer_id=self.producer_id, epoch=epoch,
                          _trace_ctx=self.conn.epoch_ctx)
        # Bounded to the configured prefetch depth: a slow trainer holds at
        # most ``prefetch`` unconsumed messages instead of buffering the
        # whole epoch in client RAM (the reference's prefetch_size
        # semantics, channel/remote_channel.py:24-85).
        buf: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        stats = {"received": 0, "duplicates": 0, "seqs": set()}
        reconnects_before = self.conn.reconnects

        def prefetcher():
            # A fetch error (dead server, socket timeout past the retry
            # budget, GC'd lease) is forwarded to the consumer instead of
            # dying silently in this thread and leaving the consumer
            # blocked forever on buf.get().
            try:
                ack = -1
                dup_run = 0
                while len(stats["seqs"]) < self.num_expected:
                    if stop.is_set():
                        return
                    seq, msg = self.conn.fetch_message(
                        self.producer_id, epoch=epoch, ack=ack, stop=stop)
                    if seq in stats["seqs"]:
                        # Duplicate suppression: a replayed message we
                        # already hold is dropped, but an identical resend
                        # loop must not spin forever.
                        stats["duplicates"] += 1
                        dup_run += 1
                        if dup_run > 2 * self.num_expected + 16:
                            raise RuntimeError(
                                "resume protocol livelock: server keeps "
                                "resending already-received seqs")
                        continue
                    dup_run = 0
                    stats["seqs"].add(seq)
                    stats["received"] += 1
                    while ack + 1 in stats["seqs"]:
                        ack += 1
                    if not bounded_put(buf, msg, stop):
                        return
            except ConnectionAbortedError:
                return   # stop-aware exchange observed the shutdown
            except Exception as e:  # noqa: BLE001 — relayed to consumer
                bounded_put(buf, e, stop)

        t = threading.Thread(target=prefetcher, daemon=True)
        t.start()
        try:
            for _ in range(self.num_expected):
                try:
                    item = bounded_get(buf, alive=t.is_alive, poll=0.2)
                except QueueSourceDied:
                    raise RuntimeError(
                        "remote sampling prefetch thread died "
                        "unexpectedly") from None
                if isinstance(item, Exception):
                    raise RuntimeError(
                        f"remote sampling prefetch failed: {item}"
                    ) from item
                yield message_to_batch(item)
        finally:
            stop.set()
            # Join the prefetcher: one still blocked inside fetch_message
            # holds the connection lock, so an un-joined exit would make a
            # prompt shutdown() (or the next epoch's start request) wait
            # out rpc_timeout.  If it doesn't exit on its own, force the
            # socket closed — the blocked recv raises, the stop-aware
            # retry loop sees `stop`, and the lock is released.
            t.join(timeout=1.0)
            if t.is_alive():
                self.conn.interrupt()
                t.join(timeout=2.0)
            stats["reconnects"] = self.conn.reconnects - reconnects_before
            # Back-compat alias; the metrics registry is the unified view.
            self.epoch_stats = publish_epoch_stats(stats)
            self.conn.epoch_ctx = None

    def shutdown(self, exit_server: bool = False) -> None:
        try:
            self.conn.request(op="destroy_sampling_producer",
                              producer_id=self.producer_id, _retries=1)
            if exit_server:
                self.conn.request(op="exit", _retries=1)
        except (RuntimeError, OSError):
            pass   # unreachable server: the lease reaper collects it
        finally:
            self.conn.close()
            auto_trace_export(self._trace_export_path)
