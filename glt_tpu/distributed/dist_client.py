"""Client side of the server–client deployment.

Rebuild of ``distributed/dist_client.py`` + the pull-based
``RemoteReceivingChannel`` (channel/remote_channel.py:24-100): the client
asks the server to create a producer, kicks epochs, and prefetches sampled
messages over the socket with a configurable depth (default 4, matching
RemoteDistSamplingWorkerOptions, dist_options.py:202-254).
"""
from __future__ import annotations

import json
import queue
import socket
import threading
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from ..channel.serialization import deserialize
from ..loader.transform import Batch
from .dist_server import _KIND_JSON, _KIND_MSG, recv_frame, send_frame
from .sample_message import message_to_batch


class RemoteServerConnection:
    def __init__(self, addr: Tuple[str, int],
                 timeout: Optional[float] = 120.0):
        # Bounded waits so a dead server surfaces as an error instead of a
        # hang (the reference's RPC timeouts, dist_options.py rpc_timeout).
        self.sock = socket.create_connection(addr, timeout=timeout)
        self.sock.settimeout(timeout)
        self._lock = threading.Lock()

    def request(self, **req) -> dict:
        with self._lock:
            send_frame(self.sock, _KIND_JSON, json.dumps(req).encode())
            kind, data = recv_frame(self.sock)
        if kind != _KIND_JSON:
            raise RuntimeError("expected JSON response")
        resp = json.loads(data)
        if "error" in resp:
            raise RuntimeError(f"server error: {resp['error']}")
        return resp

    def fetch_message(self, producer_id: int):
        with self._lock:
            send_frame(self.sock, _KIND_JSON, json.dumps(
                {"op": "fetch_one_sampled_message",
                 "producer_id": producer_id}).encode())
            kind, data = recv_frame(self.sock)
        if kind != _KIND_MSG:
            raise RuntimeError(
                json.loads(data).get("error", "bad frame"))
        return deserialize(memoryview(data))

    def close(self) -> None:
        self.sock.close()


class RemoteNeighborLoader:
    """Loader iterating batches produced on a remote sampling server
    (the reference's DistLoader 'remote' mode, dist_loader.py:188-217)."""

    def __init__(
        self,
        server_addr: Tuple[str, int],
        num_neighbors: Sequence[int],
        input_nodes: np.ndarray,
        batch_size: int = 512,
        prefetch: int = 4,
        seed: int = 0,
    ):
        self.conn = RemoteServerConnection(server_addr)
        resp = self.conn.request(
            op="create_sampling_producer",
            num_neighbors=list(num_neighbors),
            input_nodes=np.asarray(input_nodes).tolist(),
            batch_size=int(batch_size),
            seed=seed)
        self.producer_id = resp["producer_id"]
        self.num_expected = resp["num_expected"]
        self.prefetch = max(1, int(prefetch))

    def __len__(self) -> int:
        return self.num_expected

    def __iter__(self) -> Iterator[Batch]:
        self.conn.request(op="start_new_epoch_sampling",
                          producer_id=self.producer_id)
        buf: "queue.Queue" = queue.Queue()
        stop = threading.Event()

        def prefetcher():
            for _ in range(self.num_expected):
                if stop.is_set():
                    return
                buf.put(self.conn.fetch_message(self.producer_id))

        t = threading.Thread(target=prefetcher, daemon=True)
        t.start()
        try:
            for _ in range(self.num_expected):
                yield message_to_batch(buf.get())
        finally:
            stop.set()

    def shutdown(self, exit_server: bool = False) -> None:
        try:
            self.conn.request(op="destroy_sampling_producer",
                              producer_id=self.producer_id)
            if exit_server:
                self.conn.request(op="exit")
        finally:
            self.conn.close()
