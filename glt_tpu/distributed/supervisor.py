"""Fleet supervision: heartbeats, deadline-bounded waits, structured exit.

The PR-4 fault-tolerance layer made the *sampling channel* survive
failures; this module makes the *run* notice them.  Three pieces:

* :class:`Supervisor` — a peer-liveness table.  Peers report in two
  ways: **passively** (``beat(name)`` called on their behalf — the
  server beats a client on every ``heartbeat`` request, a trainer beats
  its loader on every delivered batch) or **actively**
  (``watch(name, probe)`` runs a probe callable on an interval and beats
  on success — how a trainer watches a remote server it only ever
  *receives* from).  A monitor thread marks any peer silent past its
  deadline dead, fires ``on_dead`` once, and records a structured
  reason; the training loop polls :meth:`raise_if_dead` at step
  boundaries so detection cost on the hot path is one lock-free read.
* **Deadline-bounded collectives** — :func:`run_with_deadline` and
  :func:`timed_barrier` wrap the multihost barriers/collectives of
  :mod:`~glt_tpu.parallel.multihost`: a straggling or dead host turns a
  forever-hang into a :class:`BarrierTimeoutError` after a configured
  deadline.  The abandoned worker thread cannot be cancelled — the
  contract is that the caller checkpoints and *exits* (process teardown
  reclaims it), which is exactly what
  :class:`~glt_tpu.ckpt.driver.TrainLoop` does.
* **Wire integration** — :class:`DistServer` exposes ``heartbeat`` /
  ``fleet_health`` ops on the existing JSON control channel, and
  :class:`HeartbeatSender` drives them from any fleet role over its own
  :class:`~glt_tpu.distributed.dist_client.RemoteServerConnection`.

Failure response is two-tier (docs/distributed.md failure matrix):
**degrade** where a replica exists (the PR-4 client fails over across
``fallback_addrs`` mid-epoch; the supervisor records the dead primary),
else **checkpoint-and-exit** with a flushed trace and a
:class:`SupervisedExit` carrying the machine-readable reason — never a
hang: every wait in this module is deadline-bounded.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..obs import flight as _flight
from ..obs import metrics as _metrics
from ..obs.trace import current as _current_tracer

_M_DEATHS = _metrics.counter(
    "glt.supervisor.peer_deaths", "peers declared dead by deadline expiry")
_M_BEATS = _metrics.counter(
    "glt.supervisor.beats", "heartbeats recorded (all peers)")
_M_BARRIER_TIMEOUTS = _metrics.counter(
    "glt.supervisor.barrier_timeouts",
    "deadline-bounded barriers/collectives that timed out")

DEFAULT_DEADLINE_SECS = 10.0


class PeerDeadError(RuntimeError):
    """A supervised peer missed its heartbeat deadline.

    ``report`` is the machine-readable reason the checkpoint manifest and
    :class:`SupervisedExit` carry."""

    def __init__(self, peer: str, age_s: float, deadline_s: float):
        super().__init__(
            f"peer {peer!r} silent for {age_s:.2f}s "
            f"(deadline {deadline_s:.2f}s)")
        self.report = {"reason": "peer_dead", "peer": peer,
                       "silent_s": round(age_s, 3),
                       "deadline_s": deadline_s}


class BarrierTimeoutError(RuntimeError):
    """A multihost barrier/collective exceeded its deadline — a dead or
    straggling host.  The wrapped call's thread is abandoned (it cannot
    be cancelled); checkpoint and exit."""

    def __init__(self, what: str, timeout_s: float):
        super().__init__(
            f"{what} did not complete within {timeout_s:.2f}s "
            f"(dead or straggling peer); checkpoint and exit")
        self.report = {"reason": "barrier_timeout", "what": what,
                       "deadline_s": timeout_s}


class SupervisedExit(RuntimeError):
    """A supervised run ended early — ON PURPOSE, with its state saved.

    Carries the structured ``report`` (why), the global step, and the
    emergency checkpoint path (None when no checkpointer was attached).
    """

    def __init__(self, report: Dict[str, Any], step: int,
                 checkpoint_path: Optional[str]):
        super().__init__(
            f"supervised exit at step {step}: {report.get('reason')} "
            f"({report})")
        self.report = dict(report)
        self.step = int(step)
        self.checkpoint_path = checkpoint_path


@dataclasses.dataclass
class _Peer:
    name: str
    deadline_s: float
    last_seen: float                 # monotonic
    step: Optional[int] = None
    dead: bool = False
    died_after_s: Optional[float] = None
    beats: int = 0


def run_with_deadline(fn: Callable[[], Any], timeout_s: float,
                      what: str = "collective") -> Any:
    """Run ``fn`` with a hard deadline; raises :class:`BarrierTimeoutError`.

    The call runs in a daemon thread; on timeout the thread is abandoned
    (a hung gloo/ICI collective is not interruptible from Python) and the
    structured error is raised HERE, bounded — turning the
    characteristic multihost failure mode (silent forever-hang) into a
    checkpointable event.  ``fn``'s own exception is re-raised if it
    finishes by failing.
    """
    box: List[Any] = []
    err: List[BaseException] = []

    def runner():
        try:
            box.append(fn())
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            err.append(e)

    t = threading.Thread(target=runner, daemon=True,
                         name=f"deadline-{what}")
    t.start()
    t.join(timeout=float(timeout_s))
    if t.is_alive():
        _M_BARRIER_TIMEOUTS.inc()
        _flight.record("supervisor.barrier_timeout", what=what,
                       deadline_s=float(timeout_s))
        tracer = _current_tracer()
        if tracer is not None:
            tracer.instant("supervisor.barrier_timeout", what=what,
                           deadline_s=float(timeout_s))
        raise BarrierTimeoutError(what, float(timeout_s))
    if err:
        raise err[0]
    return box[0] if box else None


def timed_barrier(name: str, timeout_s: float = DEFAULT_DEADLINE_SECS
                  ) -> None:
    """A multihost barrier that cannot hang past ``timeout_s``.

    Single-process meshes return immediately (the degenerate case every
    :mod:`~glt_tpu.parallel.multihost` helper supports); a fleet runs
    ``sync_global_devices`` under :func:`run_with_deadline`.
    """
    import jax

    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    run_with_deadline(
        lambda: multihost_utils.sync_global_devices(name),
        timeout_s, what=f"barrier {name!r}")


class Supervisor:
    """Heartbeat table + deadline monitor over a set of named peers.

    Thread-safe; the monitor thread starts lazily with the first
    registered/beaten peer and polls at ``poll_interval`` (default
    deadline/4, floored at 50 ms — detection latency is at most one poll
    past the deadline).
    """

    def __init__(self, deadline_secs: float = DEFAULT_DEADLINE_SECS,
                 poll_interval: Optional[float] = None,
                 on_dead: Optional[Callable[[str, Dict[str, Any]], None]]
                 = None):
        self.deadline_secs = float(deadline_secs)
        self.poll_interval = (max(0.05, self.deadline_secs / 4.0)
                              if poll_interval is None
                              else float(poll_interval))
        self.on_dead = on_dead
        self._peers: Dict[str, _Peer] = {}
        self._watchers: List[threading.Thread] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._dead_reports: List[Dict[str, Any]] = []

    # -- peer reporting ----------------------------------------------------
    def register(self, name: str,
                 deadline_secs: Optional[float] = None) -> None:
        """Start supervising ``name`` (the clock starts now)."""
        with self._lock:
            self._peers[name] = _Peer(
                name=name,
                deadline_s=(self.deadline_secs if deadline_secs is None
                            else float(deadline_secs)),
                last_seen=time.monotonic())
        self._ensure_monitor()

    def beat(self, name: str, step: Optional[int] = None) -> None:
        """Record a sign of life from ``name`` (auto-registers)."""
        now = time.monotonic()
        with self._lock:
            peer = self._peers.get(name)
            if peer is None:
                peer = self._peers[name] = _Peer(
                    name=name, deadline_s=self.deadline_secs, last_seen=now)
            peer.last_seen = now
            if step is not None:
                peer.step = int(step)
            revived = peer.dead
            # A resurrected peer (restarted process, resumed run) clears
            # its death mark — supervision resumes cleanly.
            peer.dead = False
            peer.beats += 1
            beats = peer.beats
        _M_BEATS.inc()
        # Black-box breadcrumbs, sampled: the first beat, every 32nd
        # (a trainer beating its loader per batch must not flush the
        # ring), and any beat that revives a declared-dead peer.
        if revived or beats == 1 or beats % 32 == 0:
            _flight.record("supervisor.beat", peer=name, beats=beats,
                           step=step, revived=revived)
        self._ensure_monitor()

    def watch(self, name: str, probe: Callable[[], Any],
              interval: Optional[float] = None,
              deadline_secs: Optional[float] = None) -> None:
        """Actively probe a peer: ``probe()`` is called every ``interval``
        seconds on a daemon thread; each SUCCESSFUL call beats ``name``
        (exceptions are swallowed — a failing probe simply lets the
        deadline expire).  How a trainer watches a server it only
        receives from: pass a cheap request on a dedicated connection.
        """
        self.register(name, deadline_secs=deadline_secs)
        ivl = (max(0.05, self.poll_interval)
               if interval is None else float(interval))

        def loop():
            while not self._stop.wait(ivl):
                try:
                    probe()
                except Exception:  # noqa: BLE001 — silence IS the signal
                    continue
                self.beat(name)

        t = threading.Thread(target=loop, daemon=True,
                             name=f"supervisor-watch-{name}")
        t.start()
        self._watchers.append(t)

    # -- monitoring --------------------------------------------------------
    def _ensure_monitor(self) -> None:
        if self._monitor is not None and self._monitor.is_alive():
            return
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="supervisor-monitor")
            self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            now = time.monotonic()
            newly_dead: List[_Peer] = []
            with self._lock:
                for peer in self._peers.values():
                    if peer.dead:
                        continue
                    age = now - peer.last_seen
                    if age > peer.deadline_s:
                        peer.dead = True
                        peer.died_after_s = age
                        newly_dead.append(peer)
            for peer in newly_dead:
                _M_DEATHS.inc()
                report = PeerDeadError(peer.name, peer.died_after_s,
                                       peer.deadline_s).report
                with self._lock:
                    self._dead_reports.append(report)
                _flight.record("supervisor.peer_dead", **report)
                tracer = _current_tracer()
                if tracer is not None:
                    tracer.instant("supervisor.peer_dead", **report)
                if self.on_dead is not None:
                    try:
                        self.on_dead(peer.name, report)
                    except Exception:  # noqa: BLE001 — monitor must live
                        pass

    # -- queries -----------------------------------------------------------
    def status(self) -> Dict[str, Dict[str, Any]]:
        """Structured health table (the ``fleet_health`` op's payload).

        ``stale_after_s`` is the structured staleness verdict: seconds of
        remaining silence before this peer's deadline expires (negative
        once it is already past).  Callers — the fleet router above all —
        read the sign instead of re-implementing ``deadline - age``
        themselves, so the deadline math lives in exactly one place.
        """
        now = time.monotonic()
        with self._lock:
            return {
                p.name: {
                    "alive": not p.dead,
                    "age_s": round(now - p.last_seen, 3),
                    "deadline_s": p.deadline_s,
                    "stale_after_s": round(
                        p.deadline_s - (now - p.last_seen), 3),
                    "step": p.step,
                }
                for p in self._peers.values()
            }

    def dead_peers(self) -> List[str]:
        with self._lock:
            return [p.name for p in self._peers.values() if p.dead]

    def raise_if_dead(self) -> None:
        """Raise :class:`PeerDeadError` for the first dead peer (the
        step-boundary poll the training loop makes)."""
        with self._lock:
            for p in self._peers.values():
                if p.dead:
                    raise PeerDeadError(
                        p.name, p.died_after_s or 0.0, p.deadline_s)

    def stop(self) -> None:
        self._stop.set()


class HeartbeatSender:
    """Periodic ``heartbeat`` requests from a fleet role to the server.

    Rides the existing JSON control channel — reconnect/backoff/failover
    come free from :class:`~glt_tpu.distributed.dist_client.
    RemoteServerConnection`.  ``step_fn`` (optional) supplies the current
    training step for the server's health table.  Failures are counted
    but swallowed: a peer that cannot reach the server simply goes
    silent, which is exactly the signal the server-side supervisor
    converts into a death after the deadline.
    """

    def __init__(self, conn, name: str, interval_secs: float = 1.0,
                 step_fn: Optional[Callable[[], int]] = None):
        self.conn = conn
        self.name = str(name)
        self.interval_secs = float(interval_secs)
        self.step_fn = step_fn
        self.failures = 0
        self.sent = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"heartbeat-{name}")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_secs):
            req = {"op": "heartbeat", "peer": self.name}
            if self.step_fn is not None:
                try:
                    req["step"] = int(self.step_fn())
                except Exception:  # noqa: BLE001 — metadata only
                    pass
            try:
                self.conn.request(_stop=self._stop, _retries=0, **req)
                self.sent += 1
            except Exception:  # noqa: BLE001 — silence IS the signal
                self.failures += 1

    def stop(self, join: bool = True) -> None:
        self._stop.set()
        if join:
            self._thread.join(timeout=2.0 + self.interval_secs)
