"""SamplerOutput/Batch <-> flat SampleMessage conversion.

Rebuild of the reference's message flattening
(dist_neighbor_sampler.py:600-673 ``_colloate_fn``): everything a batch
carries is flattened into a string-keyed dict of host arrays with ``#META.*``
scalar keys, shipped over a channel, and reconstructed loader-side
(dist_loader.py:246-383).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..channel.base import SampleMessage
from ..loader.transform import Batch

_META_BS = "#META.batch_size"


def batch_to_message(batch: Batch) -> SampleMessage:
    msg: SampleMessage = {
        "node": np.asarray(batch.node),
        "row": np.asarray(batch.edge_index[0]),
        "col": np.asarray(batch.edge_index[1]),
        "node_mask": np.asarray(batch.node_mask),
        "edge_mask": np.asarray(batch.edge_mask),
        _META_BS: np.array(batch.batch_size, np.int64),
    }
    if batch.edge_id is not None:
        msg["edge"] = np.asarray(batch.edge_id)
    if batch.batch is not None:
        msg["batch"] = np.asarray(batch.batch)
    if batch.x is not None:
        msg["x"] = np.asarray(batch.x)
    if batch.y is not None:
        msg["y"] = np.asarray(batch.y)
    if batch.metadata:
        for k, v in batch.metadata.items():
            msg[f"#META.{k}"] = np.asarray(v)
    return msg


def message_to_batch(msg: SampleMessage, to_device: bool = True) -> Batch:
    conv = jnp.asarray if to_device else np.asarray
    meta = {k[len("#META."):]: conv(v) for k, v in msg.items()
            if k.startswith("#META.") and k != _META_BS}
    return Batch(
        x=conv(msg["x"]) if "x" in msg else None,
        y=conv(msg["y"]) if "y" in msg else None,
        edge_index=jnp.stack([conv(msg["row"]), conv(msg["col"])])
        if to_device else np.stack([msg["row"], msg["col"]]),
        edge_id=conv(msg["edge"]) if "edge" in msg else None,
        node=conv(msg["node"]),
        node_mask=conv(msg["node_mask"]),
        edge_mask=conv(msg["edge_mask"]),
        batch=conv(msg["batch"]) if "batch" in msg else None,
        batch_size=int(np.asarray(msg[_META_BS]).ravel()[0]),
        metadata=meta or None,
    )
