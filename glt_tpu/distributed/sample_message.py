"""SamplerOutput/Batch <-> flat SampleMessage conversion.

Rebuild of the reference's message flattening
(dist_neighbor_sampler.py:600-673 ``_colloate_fn``): everything a batch
carries is flattened into a string-keyed dict of host arrays with ``#META.*``
scalar keys, shipped over a channel, and reconstructed loader-side
(dist_loader.py:246-383).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..channel.base import SampleMessage
from ..loader.transform import Batch

_META_BS = "#META.batch_size"


def batch_to_message(batch: Batch) -> SampleMessage:
    msg: SampleMessage = {
        "node": np.asarray(batch.node),
        "row": np.asarray(batch.edge_index[0]),
        "col": np.asarray(batch.edge_index[1]),
        "node_mask": np.asarray(batch.node_mask),
        "edge_mask": np.asarray(batch.edge_mask),
        _META_BS: np.array(batch.batch_size, np.int64),
    }
    if batch.edge_id is not None:
        msg["edge"] = np.asarray(batch.edge_id)
    if batch.batch is not None:
        msg["batch"] = np.asarray(batch.batch)
    if batch.x is not None:
        msg["x"] = np.asarray(batch.x)
    if batch.y is not None:
        msg["y"] = np.asarray(batch.y)
    if batch.metadata:
        for k, v in batch.metadata.items():
            msg[f"#META.{k}"] = np.asarray(v)
    return msg


_HET = "#HETERO"
_ET_SEP = "|"


def _et_key(et) -> str:
    if any(_ET_SEP in part for part in et):
        raise ValueError(
            f"edge-type components must not contain {_ET_SEP!r} "
            f"(got {et!r}); rename the relation for channel transport")
    return _ET_SEP.join(et)


def _et_parse(s: str):
    a, b, c = s.split(_ET_SEP)
    return (a, b, c)


def hetero_batch_to_message(batch) -> SampleMessage:
    """Flatten a :class:`HeteroBatch` into string-keyed host arrays
    (the reference's ``#IS_HETERO`` / per-type key flattening,
    dist_neighbor_sampler.py:600-673)."""
    msg: SampleMessage = {
        _HET: np.array(1, np.int64),
        _META_BS: np.array(batch.batch_size, np.int64),
        "#input_type": np.frombuffer(
            str(batch.input_type).encode(), dtype=np.uint8).copy(),
    }
    for t, v in batch.node.items():
        msg[f"node@{t}"] = np.asarray(v)
    for t, v in batch.node_mask.items():
        msg[f"node_mask@{t}"] = np.asarray(v)
    for et, v in batch.edge_index.items():
        msg[f"ei@{_et_key(et)}"] = np.asarray(v)
    for et, v in (batch.edge_id or {}).items():
        if v is not None:
            msg[f"eid@{_et_key(et)}"] = np.asarray(v)
    for et, v in batch.edge_mask.items():
        msg[f"em@{_et_key(et)}"] = np.asarray(v)
    for t, v in (batch.x or {}).items():
        msg[f"x@{t}"] = np.asarray(v)
    for t, v in (batch.y or {}).items():
        msg[f"y@{t}"] = np.asarray(v)
    for t, v in (batch.batch or {}).items():
        msg[f"batch@{t}"] = np.asarray(v)
    for k, v in (batch.metadata or {}).items():
        msg[f"#META.{k}"] = np.asarray(v)
    return msg


def message_to_hetero_batch(msg: SampleMessage, to_device: bool = True):
    from ..loader.transform import HeteroBatch

    conv = jnp.asarray if to_device else np.asarray

    def group(prefix, et=False):
        out = {}
        for k, v in msg.items():
            if k.startswith(prefix + "@"):
                key = k[len(prefix) + 1:]
                out[_et_parse(key) if et else key] = conv(v)
        return out

    meta = {k[len("#META."):]: conv(v) for k, v in msg.items()
            if k.startswith("#META.") and k != _META_BS}
    return HeteroBatch(
        x=group("x") or {},
        y=group("y") or None,
        edge_index=group("ei", et=True),
        edge_id=group("eid", et=True),
        node=group("node"),
        node_mask=group("node_mask"),
        edge_mask=group("em", et=True),
        batch=group("batch") or None,
        batch_size=int(np.asarray(msg[_META_BS]).ravel()[0]),
        input_type=bytes(np.asarray(msg["#input_type"])).decode(),
        metadata=meta or None,
    )


def message_to_batch(msg: SampleMessage, to_device: bool = True):
    """Reconstruct a Batch — or a HeteroBatch when the hetero marker is
    present (cf. the reference's #IS_HETERO dispatch, dist_loader.py:286)."""
    if _HET in msg:
        return message_to_hetero_batch(msg, to_device=to_device)
    conv = jnp.asarray if to_device else np.asarray
    meta = {k[len("#META."):]: conv(v) for k, v in msg.items()
            if k.startswith("#META.") and k != _META_BS}
    return Batch(
        x=conv(msg["x"]) if "x" in msg else None,
        y=conv(msg["y"]) if "y" in msg else None,
        edge_index=jnp.stack([conv(msg["row"]), conv(msg["col"])])
        if to_device else np.stack([msg["row"], msg["col"]]),
        edge_id=conv(msg["edge"]) if "edge" in msg else None,
        node=conv(msg["node"]),
        node_mask=conv(msg["node_mask"]),
        edge_mask=conv(msg["edge_mask"]),
        batch=conv(msg["batch"]) if "batch" in msg else None,
        batch_size=int(np.asarray(msg[_META_BS]).ravel()[0]),
        metadata=meta or None,
    )
