"""Sampling worker options (cf. distributed/dist_options.py).

The reference selects its loader mode by option type (dist_loader.py:
142-221): collocated (sync in-process), mp (sampling subprocesses + shm
channel), or remote (server-side producers).  The TPU build keeps the same
pattern; the remote mode's options are
:class:`RemoteSamplingWorkerOptions`, consumed by
:class:`~glt_tpu.distributed.dist_client.RemoteNeighborLoader` and
forwarded to the server's producer factory.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class CollocatedSamplingWorkerOptions:
    """Sample in-process, synchronously (the default fused-on-device path)."""


@dataclasses.dataclass
class MpSamplingWorkerOptions:
    """Sample in ``num_workers`` CPU subprocesses feeding a shm channel.

    Mirrors ``MpDistSamplingWorkerOptions`` (dist_options.py:202-254):
    per-worker channel capacity, pinned host staging, worker seeds split
    batch-aligned (dist_sampling_producer.py:229-247).
    """
    num_workers: int = 2
    channel_capacity_bytes: int = 64 * 1024 * 1024
    worker_seed: int = 0
    # Trainer-side recv timeout (seconds) between worker-liveness checks;
    # bounds how long a mid-epoch worker death can stall the epoch.
    heartbeat_secs: float = 5.0


@dataclasses.dataclass
class RemoteSamplingWorkerOptions:
    """Sample on a remote server; producers run there, batches stream back.

    Mirrors ``RemoteDistSamplingWorkerOptions`` (dist_options.py:202-254):
    the client sets the server-side producer shape (worker count, buffer
    bounds) and its own prefetch depth.

    Attributes:
      num_workers: sampling subprocesses the server spawns for this
        producer (0 = one in-server thread; >0 needs the server to have
        been started with a picklable ``dataset_builder``).
      buffer_capacity: server-side bounded buffer, in messages (the
        reference's per-producer shm buffer capacity).
      channel_capacity_bytes: shm ring size for the server's mp workers.
      prefetch_size: client-side prefetch depth — at most this many
        fetched-but-unconsumed messages are held by the loader (the
        reference's RemoteReceivingChannel prefetch, remote_channel.py:24).
      max_retries: retryable transport failures (timeout, ECONNRESET,
        EOF, desynced frame) per exchange before giving up; each retry
        reconnects with exponential backoff + jitter.
      backoff_base / backoff_cap: reconnect backoff schedule, seconds —
        ``min(cap, base * 2**attempt)`` with 50-100% jitter.
      fallback_addrs: replica ``(host, port)`` addresses tried when the
        primary is unreachable (failover for meta/create traffic; a
        mid-epoch producer cannot migrate, so a failed-over fetch
        surfaces ``UnknownProducerError``).
      lease_secs: server-side producer lease; renewed implicitly by any
        request naming the producer, including every poll of a blocked
        fetch.  A client that vanishes without destroy leaks nothing —
        the server reaper GCs the producer (mp fleet + shm segment)
        once the lease expires.  0 disables expiry.
      replay_window: sent-but-unacked messages the server retains per
        producer for resume-after-reconnect.
      max_frame_bytes: reject protocol frames above this payload size (a
        corrupt u64 length must not drive an unbounded allocation).
      server_addr: ``(host, port)`` — only consumed by the worker-mode
        ``DistNeighborLoader`` front-end to select remote mode by option
        type; ``RemoteNeighborLoader`` takes the address positionally.
    """
    num_workers: int = 0
    buffer_capacity: int = 8
    channel_capacity_bytes: int = 64 * 1024 * 1024
    prefetch_size: int = 4
    worker_seed: int = 0
    # Socket timeout for every client<->server exchange (the reference's
    # rpc_timeout, dist_options.py:~90).  Generous default: a first XLA
    # compile on an oversubscribed host can stall the producer for
    # minutes before the first batch lands.  Latency-sensitive ops can
    # override per request (`RemoteServerConnection.request(_timeout=)` /
    # `_exchange(timeout=)`) without touching this training-path default
    # — the serving InferenceClient derives its per-op timeout from each
    # request's deadline.
    rpc_timeout: float = 600.0
    # -- fault tolerance (see docs/distributed.md "Fault tolerance") ----
    max_retries: int = 3
    backoff_base: float = 0.05
    backoff_cap: float = 2.0
    fallback_addrs: tuple = ()
    lease_secs: float = 300.0
    replay_window: int = 8
    max_frame_bytes: int = 1 << 30
    server_addr: tuple = None
