"""Sampling worker options (cf. distributed/dist_options.py).

The reference selects its loader mode by option type (dist_loader.py:
142-221): collocated (sync in-process), mp (sampling subprocesses + shm
channel), or remote (server-side producers).  The TPU build keeps the same
pattern; 'remote' is intentionally absent this round — on TPU, remote
sampling maps to separate host processes feeding the same shm channel.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class CollocatedSamplingWorkerOptions:
    """Sample in-process, synchronously (the default fused-on-device path)."""


@dataclasses.dataclass
class MpSamplingWorkerOptions:
    """Sample in ``num_workers`` CPU subprocesses feeding a shm channel.

    Mirrors ``MpDistSamplingWorkerOptions`` (dist_options.py:202-254):
    per-worker channel capacity, pinned host staging, worker seeds split
    batch-aligned (dist_sampling_producer.py:229-247).
    """
    num_workers: int = 2
    channel_capacity_bytes: int = 64 * 1024 * 1024
    worker_seed: int = 0
    # Trainer-side recv timeout (seconds) between worker-liveness checks;
    # bounds how long a mid-epoch worker death can stall the epoch.
    heartbeat_secs: float = 5.0
