"""Channel-fed loader front-end (worker mode).

Rebuild of ``distributed/dist_loader.py``: mode chosen by options type
(:142-221) — collocated falls through to the in-process
:class:`~glt_tpu.loader.node_loader.NeighborLoader`; mp mode spawns CPU
sampling subprocesses and the trainer iterates channel messages
(``__next__`` = channel recv + reconstruct, :246-383), overlapping host
sampling with device training exactly like the reference overlaps its
producer fleet with DDP compute.
"""
from __future__ import annotations

from typing import Callable, Iterator, Optional, Sequence, Union

import numpy as np

from ..channel import ShmChannel
from ..loader.node_loader import NeighborLoader
from ..loader.transform import Batch
from ..obs import metrics as _metrics
from ..obs.trace import span as _span
from .dist_options import (
    CollocatedSamplingWorkerOptions,
    MpSamplingWorkerOptions,
    RemoteSamplingWorkerOptions,
)
from .dist_sampling_producer import MpSamplingProducer, WORKER_SAMPLER_KWARGS
from .sample_message import message_to_batch


class _DistLoaderBase:
    """Shared deployment-mode plumbing for the three concrete loaders
    (cf. DistLoader, dist_loader.py:142-221; concrete loaders
    dist_neighbor_loader.py:28, dist_link_neighbor_loader.py:31,
    dist_subgraph_loader.py:28).

    Collocated mode needs a live ``dataset``; mp mode needs a picklable
    ``dataset_builder`` (workers rebuild the dataset host-side).
    """

    _KIND = "node"
    # When set, mp mode rejects kind_kwargs outside this set (workers would
    # silently drop them); None means the subclass's explicit signature
    # already bounds what reaches the workers.
    _ALLOWED_MP_KWARGS: Optional[frozenset] = None

    def __init__(
        self,
        num_neighbors: Sequence[int],
        input_seeds: np.ndarray,
        batch_size: int = 512,
        shuffle: bool = False,
        dataset=None,
        dataset_builder: Optional[Callable] = None,
        builder_args: tuple = (),
        worker_options=None,
        seed: int = 0,
        **kind_kwargs,
    ):
        worker_options = worker_options or CollocatedSamplingWorkerOptions()
        self.options = worker_options
        self._inner = None
        self._remote = None
        self._producer: Optional[MpSamplingProducer] = None

        if isinstance(worker_options, RemoteSamplingWorkerOptions):
            # Remote mode by option type (the reference's DistLoader mode
            # select, dist_loader.py:142-221): producers live on the
            # sampling server named by ``worker_options.server_addr``;
            # batches stream back over the fault-tolerant socket protocol.
            if worker_options.server_addr is None:
                raise ValueError(
                    "remote mode requires "
                    "RemoteSamplingWorkerOptions(server_addr=(host, port))")
            if self._KIND != "node":
                raise NotImplementedError(
                    f"remote mode serves node sampling only (got "
                    f"{self._KIND!r}); use an mp/collocated loader")
            from .dist_client import RemoteNeighborLoader

            self._remote = RemoteNeighborLoader(
                tuple(worker_options.server_addr), num_neighbors,
                input_seeds, batch_size=batch_size, seed=seed,
                worker_options=worker_options)
            self._inner = self._remote
        elif isinstance(worker_options, CollocatedSamplingWorkerOptions):
            if dataset is None:
                raise ValueError("collocated mode requires dataset=")
            self._inner = self._make_inner(
                dataset, num_neighbors, input_seeds, batch_size, shuffle,
                seed, kind_kwargs)
        elif isinstance(worker_options, MpSamplingWorkerOptions):
            if dataset_builder is None:
                raise ValueError("mp mode requires dataset_builder=")
            if self._ALLOWED_MP_KWARGS is not None:
                bad = set(kind_kwargs) - self._ALLOWED_MP_KWARGS
                if bad:
                    raise TypeError(
                        f"mp sampling workers do not support {sorted(bad)}"
                        f" (collocated mode only)")
            self.channel = ShmChannel(
                capacity_bytes=worker_options.channel_capacity_bytes)
            self._producer = MpSamplingProducer(
                dataset_builder, builder_args, num_neighbors, input_seeds,
                batch_size, worker_options, self.channel, shuffle=shuffle,
                kind=self._KIND, kind_kwargs=kind_kwargs or None, seed=seed)
            self._producer.init()
            self._num_batches = self._producer.num_expected()
        else:
            raise TypeError(f"unknown worker options {worker_options!r}")

    def _make_inner(self, dataset, num_neighbors, input_seeds, batch_size,
                    shuffle, seed, kind_kwargs):
        raise NotImplementedError

    def __iter__(self) -> Iterator[Batch]:
        if self._inner is not None:
            yield from self._inner
            return
        # epoch protocol (cf. dist_loader.py:259-272); iter_messages
        # survives mid-epoch worker death (recv heartbeat + seed reissue).
        with _span("dist_loader.mp_epoch"):
            self._producer.produce_all()
            mp_batches = _metrics.counter(
                "glt.loader.mp_batches",
                "batches received over the shm channel (mp mode)")
            for msg in self._producer.iter_messages():
                mp_batches.inc()
                yield message_to_batch(msg)

    def __len__(self) -> int:
        if self._inner is not None:
            return len(self._inner)
        return self._num_batches

    def shutdown(self) -> None:
        if self._remote is not None:
            self._remote.shutdown()
            self._remote = None
            self._inner = None
        if self._producer is not None:
            self._producer.shutdown()
            self.channel.close()
            self._producer = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


class DistNeighborLoader(_DistLoaderBase):
    """Worker-mode neighbor loader (cf. dist_neighbor_loader.py:28).

    ``input_seeds`` are global seed node ids; each delivered :class:`Batch`
    is a fully-collated multi-hop sample (features/labels gathered
    worker-side in mp mode, in-process in collocated mode).
    """

    _KIND = "node"
    _ALLOWED_MP_KWARGS = WORKER_SAMPLER_KWARGS

    def _make_inner(self, dataset, num_neighbors, input_seeds, batch_size,
                    shuffle, seed, kind_kwargs):
        return NeighborLoader(
            dataset, num_neighbors, input_seeds, batch_size=batch_size,
            shuffle=shuffle, seed=seed, **kind_kwargs)


class DistHeteroNeighborLoader(_DistLoaderBase):
    """Worker-mode heterogeneous neighbor loader.

    The reference reaches hetero through the same DistNeighborLoader with
    a (type, ids) seed tuple (dist_neighbor_loader.py:28 +
    dist_neighbor_sampler.py:270-288); here the hetero front-end is its
    own class for static typing of the delivered :class:`HeteroBatch`.
    ``input_nodes`` is ``(node_type, ids)``; channel messages carry the
    per-type flattening (sample_message.hetero_batch_to_message).
    """

    _KIND = "hetero_node"

    def __init__(
        self,
        num_neighbors,
        input_nodes,
        batch_size: int = 512,
        shuffle: bool = False,
        frontier_cap: Optional[int] = None,
        dataset=None,
        dataset_builder: Optional[Callable] = None,
        builder_args: tuple = (),
        worker_options=None,
        seed: int = 0,
    ):
        if not (isinstance(input_nodes, tuple) and len(input_nodes) == 2):
            raise ValueError(
                "input_nodes must be (node_type, ids) for hetero loading")
        input_type, ids = input_nodes
        super().__init__(
            num_neighbors, np.asarray(ids).astype(np.int64),
            batch_size=batch_size, shuffle=shuffle, dataset=dataset,
            dataset_builder=dataset_builder, builder_args=builder_args,
            worker_options=worker_options, seed=seed,
            input_type=input_type, frontier_cap=frontier_cap)

    def _make_inner(self, dataset, num_neighbors, input_seeds, batch_size,
                    shuffle, seed, kind_kwargs):
        from ..loader.hetero_neighbor_loader import HeteroNeighborLoader

        return HeteroNeighborLoader(
            dataset, num_neighbors,
            (kind_kwargs["input_type"], input_seeds),
            batch_size=batch_size, shuffle=shuffle,
            frontier_cap=kind_kwargs.get("frontier_cap"), seed=seed)


class DistLinkNeighborLoader(_DistLoaderBase):
    """Worker-mode link loader (cf. dist_link_neighbor_loader.py:31).

    Seed *edges* drive ``sample_from_edges``; the channel messages carry
    ``edge_label_index`` / ``edge_label`` (binary) or triplet indices, the
    same metadata the collocated :class:`LinkNeighborLoader` emits.
    """

    _KIND = "link"

    def __init__(
        self,
        num_neighbors: Sequence[int],
        edge_label_index: np.ndarray,
        edge_label: Optional[np.ndarray] = None,
        neg_sampling=None,
        batch_size: int = 512,
        shuffle: bool = False,
        dataset=None,
        dataset_builder: Optional[Callable] = None,
        builder_args: tuple = (),
        worker_options=None,
        seed: int = 0,
    ):
        eli = np.asarray(edge_label_index).astype(np.int64)
        lab = None if edge_label is None else np.asarray(edge_label)
        super().__init__(
            num_neighbors, np.arange(eli.shape[1], dtype=np.int64),
            batch_size=batch_size, shuffle=shuffle, dataset=dataset,
            dataset_builder=dataset_builder, builder_args=builder_args,
            worker_options=worker_options, seed=seed,
            edge_label_index=eli, edge_label=lab, neg_sampling=neg_sampling)

    def _make_inner(self, dataset, num_neighbors, input_seeds, batch_size,
                    shuffle, seed, kind_kwargs):
        from ..loader.link_loader import LinkNeighborLoader

        return LinkNeighborLoader(
            dataset, num_neighbors, kind_kwargs["edge_label_index"],
            edge_label=kind_kwargs.get("edge_label"),
            neg_sampling=kind_kwargs.get("neg_sampling"),
            batch_size=batch_size, shuffle=shuffle, seed=seed)


class DistSubGraphLoader(_DistLoaderBase):
    """Worker-mode induced-subgraph loader (cf. dist_subgraph_loader.py:28)."""

    _KIND = "subgraph"

    def __init__(
        self,
        num_neighbors: Sequence[int],
        input_seeds: np.ndarray,
        batch_size: int = 64,
        max_degree: int = 64,
        shuffle: bool = False,
        dataset=None,
        dataset_builder: Optional[Callable] = None,
        builder_args: tuple = (),
        worker_options=None,
        seed: int = 0,
    ):
        super().__init__(
            num_neighbors, input_seeds, batch_size=batch_size,
            shuffle=shuffle, dataset=dataset,
            dataset_builder=dataset_builder, builder_args=builder_args,
            worker_options=worker_options, seed=seed, max_degree=max_degree)

    def _make_inner(self, dataset, num_neighbors, input_seeds, batch_size,
                    shuffle, seed, kind_kwargs):
        from ..loader.subgraph_loader import SubGraphLoader

        return SubGraphLoader(
            dataset, num_neighbors, input_seeds, batch_size=batch_size,
            max_degree=kind_kwargs["max_degree"], shuffle=shuffle, seed=seed)
