"""Channel-fed loader front-end (worker mode).

Rebuild of ``distributed/dist_loader.py``: mode chosen by options type
(:142-221) — collocated falls through to the in-process
:class:`~glt_tpu.loader.node_loader.NeighborLoader`; mp mode spawns CPU
sampling subprocesses and the trainer iterates channel messages
(``__next__`` = channel recv + reconstruct, :246-383), overlapping host
sampling with device training exactly like the reference overlaps its
producer fleet with DDP compute.
"""
from __future__ import annotations

from typing import Callable, Iterator, Optional, Sequence, Union

import numpy as np

from ..channel import ShmChannel
from ..loader.node_loader import NeighborLoader
from ..loader.transform import Batch
from .dist_options import (
    CollocatedSamplingWorkerOptions,
    MpSamplingWorkerOptions,
)
from .dist_sampling_producer import MpSamplingProducer
from .sample_message import message_to_batch


class _DistLoaderBase:
    """Shared deployment-mode plumbing for the three concrete loaders
    (cf. DistLoader, dist_loader.py:142-221; concrete loaders
    dist_neighbor_loader.py:28, dist_link_neighbor_loader.py:31,
    dist_subgraph_loader.py:28).

    Collocated mode needs a live ``dataset``; mp mode needs a picklable
    ``dataset_builder`` (workers rebuild the dataset host-side).
    """

    _KIND = "node"

    def __init__(
        self,
        num_neighbors: Sequence[int],
        input_seeds: np.ndarray,
        batch_size: int = 512,
        shuffle: bool = False,
        dataset=None,
        dataset_builder: Optional[Callable] = None,
        builder_args: tuple = (),
        worker_options=None,
        seed: int = 0,
        **kind_kwargs,
    ):
        worker_options = worker_options or CollocatedSamplingWorkerOptions()
        self.options = worker_options
        self._inner = None
        self._producer: Optional[MpSamplingProducer] = None

        if isinstance(worker_options, CollocatedSamplingWorkerOptions):
            if dataset is None:
                raise ValueError("collocated mode requires dataset=")
            self._inner = self._make_inner(
                dataset, num_neighbors, input_seeds, batch_size, shuffle,
                seed, kind_kwargs)
        elif isinstance(worker_options, MpSamplingWorkerOptions):
            if dataset_builder is None:
                raise ValueError("mp mode requires dataset_builder=")
            self.channel = ShmChannel(
                capacity_bytes=worker_options.channel_capacity_bytes)
            self._producer = MpSamplingProducer(
                dataset_builder, builder_args, num_neighbors, input_seeds,
                batch_size, worker_options, self.channel, shuffle=shuffle,
                kind=self._KIND, kind_kwargs=kind_kwargs or None)
            self._producer.init()
        else:
            raise TypeError(f"unknown worker options {worker_options!r}")

    def _make_inner(self, dataset, num_neighbors, input_seeds, batch_size,
                    shuffle, seed, kind_kwargs):
        raise NotImplementedError

    def __iter__(self) -> Iterator[Batch]:
        if self._inner is not None:
            yield from self._inner
            return
        # epoch protocol (cf. dist_loader.py:259-272); iter_messages
        # survives mid-epoch worker death (recv heartbeat + seed reissue).
        self._producer.produce_all()
        for msg in self._producer.iter_messages():
            yield message_to_batch(msg)

    def __len__(self) -> int:
        if self._inner is not None:
            return len(self._inner)
        return self._producer.num_expected()

    def shutdown(self) -> None:
        if self._producer is not None:
            self._producer.shutdown()
            self.channel.close()
            self._producer = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
