"""Process-role bookkeeping for distributed deployments.

Rebuild of ``distributed/dist_context.py:20-183``.  On TPU the data-plane
rank/world bookkeeping lives in the device mesh (``jax.sharding.Mesh`` —
every in-jit collective is rank-addressed by the mesh axis), so this
module only tracks the **host-process role topology** the server-client
deployment needs: which role this process plays (WORKER / SERVER /
CLIENT), its rank within the role group, and the global fleet shape —
enough to express multi-server × multi-client topologies
(tests/test_server_client.py::test_two_servers_two_clients).
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Optional


class DistRole(enum.Enum):
    WORKER = 1   # non-server worker group
    SERVER = 2   # sampling server (server-client mode)
    CLIENT = 3   # trainer client (server-client mode)


@dataclass(frozen=True)
class DistContext:
    """Distributed context of the current process (cf. dist_context.py:33).

    ``world_size``/``rank`` are within the role group;
    ``global_world_size``/``global_rank`` span all role groups (servers
    enumerate first, then clients — the reference's naming convention).
    """
    role: DistRole
    group_name: str
    world_size: int
    rank: int
    global_world_size: int
    global_rank: int

    def __post_init__(self):
        if not (0 < self.world_size and 0 <= self.rank < self.world_size):
            raise ValueError(
                f"rank {self.rank} not in [0, {self.world_size})")
        if not (self.world_size <= self.global_world_size
                and 0 <= self.global_rank < self.global_world_size):
            raise ValueError(
                f"global rank {self.global_rank} / world "
                f"{self.global_world_size} inconsistent with role world "
                f"{self.world_size}")

    def is_worker(self) -> bool:
        return self.role == DistRole.WORKER

    def is_server(self) -> bool:
        return self.role == DistRole.SERVER

    def is_client(self) -> bool:
        return self.role == DistRole.CLIENT

    def num_servers(self) -> int:
        if self.role == DistRole.SERVER:
            return self.world_size
        if self.role == DistRole.CLIENT:
            return self.global_world_size - self.world_size
        return 0

    def num_clients(self) -> int:
        if self.role == DistRole.CLIENT:
            return self.world_size
        if self.role == DistRole.SERVER:
            return self.global_world_size - self.world_size
        return 0

    @property
    def worker_name(self) -> str:
        return f"{self.group_name}-{self.rank}"


_lock = threading.Lock()
_context: Optional[DistContext] = None


def get_context() -> Optional[DistContext]:
    return _context


def _set(ctx: DistContext) -> DistContext:
    global _context
    with _lock:
        _context = ctx
    return ctx


def _set_default(ctx: DistContext) -> DistContext:
    """Install ``ctx`` as the process context only if none is set.

    Used by in-process conveniences (e.g. DistServer construction) so
    that hosting several roles in one process — the single-host test
    topology — does not silently last-writer-win the global; explicit
    ``init_*_context`` calls always overwrite.
    """
    global _context
    with _lock:
        if _context is None:
            _context = ctx
    return ctx


def init_worker_group(world_size: int = 1, rank: int = 0,
                      group_name: str = "_default_worker") -> DistContext:
    """Declare this process a worker (cf. init_worker_group,
    dist_context.py:169)."""
    return _set(DistContext(DistRole.WORKER, group_name, world_size, rank,
                            world_size, rank))


def make_server_context(num_servers: int, server_rank: int,
                        num_clients: int = 0,
                        group_name: str = "_default_server") -> DistContext:
    """Build (without installing) a SERVER context; servers take global
    ranks [0, num_servers), clients follow — the reference's convention."""
    return DistContext(
        DistRole.SERVER, group_name, num_servers, server_rank,
        num_servers + max(num_clients, 0), server_rank)


def init_server_context(num_servers: int, server_rank: int,
                        num_clients: int = 0,
                        group_name: str = "_default_server") -> DistContext:
    """Declare this process a sampling server."""
    return _set(make_server_context(num_servers, server_rank, num_clients,
                                    group_name))


def init_client_context(num_clients: int, client_rank: int,
                        num_servers: int = 0,
                        group_name: str = "_default_client") -> DistContext:
    """Declare this process a trainer client."""
    return _set(DistContext(
        DistRole.CLIENT, group_name, num_clients, client_rank,
        max(num_servers, 0) + num_clients,
        max(num_servers, 0) + client_rank))
