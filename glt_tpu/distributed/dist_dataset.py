"""Partition directory -> mesh-ready distributed dataset.

Rebuild of the reference's ``distributed/dist_dataset.py:77-164``: there,
``DistDataset.load`` reads one saved partition, merges the hot-feature cache
in front of owned rows (``cat_feature_cache``) and patches the feature
partition book so cached remote rows resolve locally.  The TPU composition
differs where the runtime differs:

* ownership must end up **arithmetic** (``owner = id // c``) for the in-jit
  all-to-all routing, so the partition books are folded into a one-time
  contiguous relabeling (:func:`~glt_tpu.partition.contiguous.contiguous_relabel`)
  instead of being consulted per lookup;
* the hot-cache has no bandwidth to save when exchanges are fixed-shape
  collectives, so hotness instead orders each partition's rows
  hottest-first and selects the **HBM prefix** of a
  :class:`~glt_tpu.parallel.dist_feature.TieredShardedFeature` — the same
  rows the reference would have cached now simply live in fast memory;
* labels ride a sharded ``[S, c]`` block (the reference reads them from a
  whole-graph label file per partition, dist_dataset.py:140-152).

This single-process loader materialises every partition (mirroring the
reference's single-host tests); on a real pod each host would load only its
shards' blocks — the layout already supports that (everything is per-part
files).
"""
from __future__ import annotations

import os
from typing import List, NamedTuple, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..parallel.dist_feature import TieredShardedFeature, shard_feature_tiered
from ..parallel.sharding import (
    ShardedFeature,
    ShardedGraph,
    shard_feature,
    shard_graph,
)
from ..partition.base import load_partition
from ..partition.contiguous import (
    ContiguousRelabel,
    contiguous_relabel,
    relabel_rows,
    relabel_topology,
)
from ..data.topology import CSRTopo


class DistDataset(NamedTuple):
    """Everything the fused distributed train step consumes."""
    graph: ShardedGraph
    feature: Optional[Union[ShardedFeature, TieredShardedFeature]]
    labels: Optional[jnp.ndarray]          # [S, nodes_per_shard], -1 padded
    relabel: ContiguousRelabel
    num_parts: int

    # -- seed handling -----------------------------------------------------
    def translate(self, old_ids: np.ndarray) -> np.ndarray:
        """Global original ids -> relabeled (mesh) ids."""
        return self.relabel.old2new[np.asarray(old_ids)]

    def split_seeds(self, old_ids: np.ndarray, batch_size: int,
                    shuffle: bool = False, seed: int = 0,
                    rng: Optional[np.random.Generator] = None) -> np.ndarray:
        """Group seeds by owner shard into ``[num_batches, S, B]`` (-1 pad).

        The per-rank disjoint seed split of the reference's trainers
        (dist_train_sage_supervised.py:76): shard ``s`` trains on the seeds
        it owns, so hop 0 of every batch needs no exchange.

        ``rng``: a *stateful* Generator threaded by the caller.  A fresh
        ``default_rng(seed)`` per call replays the identical permutation
        every epoch — multi-epoch trainers pass their epoch-advancing
        Generator (every host of a fleet must seed it identically so the
        global batch layout agrees).  ``seed`` remains for single-shot
        deterministic splits.
        """
        new = self.translate(old_ids)
        if shuffle:
            gen = rng if rng is not None else np.random.default_rng(seed)
            new = new[gen.permutation(new.shape[0])]
        c = self.relabel.nodes_per_shard
        s_count = self.num_parts
        per_shard: List[np.ndarray] = [new[new // c == s]
                                       for s in range(s_count)]
        nb = max((p.shape[0] + batch_size - 1) // batch_size
                 for p in per_shard)
        out = np.full((nb, s_count, batch_size), -1, np.int64)
        for s, ids in enumerate(per_shard):
            for b in range(nb):
                chunk = ids[b * batch_size: (b + 1) * batch_size]
                out[b, s, : chunk.shape[0]] = chunk
        return out

    @staticmethod
    def load(
        root: str,
        hot_ratio: float = 1.0,
        labels: Optional[np.ndarray] = None,
        hotness: Optional[np.ndarray] = None,
        dtype=None,
        mesh: Optional[jax.sharding.Mesh] = None,
        axis_name: str = "shard",
    ) -> "DistDataset":
        """Compose a saved partition dir into mesh-ready sharded arrays.

        Args:
          root: partitioner output directory (any PartitionerBase subclass
            or DistRandomPartitioner layout).
          hot_ratio: fraction of each shard's rows resident in HBM
            (1.0 = plain :class:`ShardedFeature`, no host tier).
          labels: optional global ``[N]`` label array (the reference's
            whole-graph label file).
          hotness: optional global ``[N]`` score ordering each partition's
            rows hottest-first; defaults to in-degree
            (``sort_by_in_degree``, reference data/reorder.py:18).
          mesh: when given, load **per host**: each process reads only the
            partitions backing its local mesh devices and feeds them into
            process-spanning global arrays
            (:mod:`~glt_tpu.parallel.multihost`) — the reference's "each
            machine loads its own partition" (dist_dataset.py:77-164).
            With in-degree hotness unavailable locally, pass ``hotness``
            explicitly (the partitioner saves one) or rows keep partition
            order.
        """
        import json

        with open(os.path.join(root, "META.json")) as fh:
            meta = json.load(fh)
        num_parts = int(meta["num_parts"])
        num_nodes = int(meta["num_nodes"])
        node_pb = np.load(os.path.join(root, "node_pb.npy"))

        if mesh is not None:
            if meta.get("edge_assign_strategy", "by_src") != "by_src":
                raise ValueError(
                    "per-host loading requires the by_src edge layout "
                    "(each partition owns its sources' out-edges)")
            return DistDataset._load_multihost(
                root, num_parts, num_nodes, node_pb, hot_ratio, labels,
                hotness, dtype, mesh, axis_name)

        # 1) gather every partition's edges + features (single-process
        #    emulation; per-host loads on a real pod).
        edge_chunks, eid_chunks = [], []
        feat_ids, feat_rows = [], []
        feat_dim = None
        for p in range(num_parts):
            graph, node_feat, _, _, _, _ = load_partition(root, p)
            edge_chunks.append(graph.edge_index)
            eid_chunks.append(graph.eids)
            if node_feat is not None:
                feat_ids.append(node_feat.ids)
                feat_rows.append(node_feat.feats)
                feat_dim = node_feat.feats.shape[1]
        edge_index = np.concatenate(edge_chunks, axis=1)
        edge_ids = np.concatenate(eid_chunks)

        # 2) hotness-ordered contiguous relabel (the cat_feature_cache
        #    analog — see module docstring).
        if hotness is None:
            hotness = np.bincount(edge_index[1], minlength=num_nodes)
        rel = contiguous_relabel(node_pb, hotness=hotness,
                                 num_parts=num_parts)

        topo = relabel_topology(
            CSRTopo(edge_index, edge_ids=edge_ids, num_nodes=num_nodes), rel)
        g = shard_graph(topo, num_parts)

        # 3) features into new-id order, then tier/shard.
        feature = None
        if feat_dim is not None:
            all_ids = np.concatenate(feat_ids)
            all_rows = np.concatenate(feat_rows)
            full = np.zeros((num_nodes, feat_dim), all_rows.dtype)
            full[all_ids.astype(np.int64)] = all_rows
            new_order = relabel_rows(full, rel)
            if hot_ratio >= 1.0:
                feature = shard_feature(new_order, num_parts, dtype=dtype)
            else:
                feature = shard_feature_tiered(new_order, num_parts,
                                               hot_ratio, dtype=dtype)

        lab = None
        if labels is not None:
            lab_new = relabel_rows(np.asarray(labels), rel, fill=-1)
            lab = jnp.asarray(
                lab_new.reshape(num_parts, rel.nodes_per_shard))

        return DistDataset(graph=g, feature=feature, labels=lab,
                           relabel=rel, num_parts=num_parts)

    @staticmethod
    def _load_multihost(root, num_parts, num_nodes, node_pb, hot_ratio,
                        labels, hotness, dtype, mesh, axis_name):
        """Per-host composition: local partitions -> global arrays.

        Every host computes the (global) contiguous relabel from the small
        ``node_pb``/``hotness`` files, loads only its own partitions'
        edges + feature rows, builds its shard blocks, and assembles them
        into process-spanning arrays.  No host materialises another
        host's partition — the property that makes papers100M-scale
        feeding possible on a pod.
        """
        from ..parallel import multihost
        from ..parallel.sharding import ShardedGraph

        if mesh.devices.size != num_parts:
            raise ValueError(
                f"mesh has {mesh.devices.size} devices but the partition "
                f"dir holds {num_parts} partitions")
        local = multihost.local_shard_range(mesh, axis_name)

        # 1) local partitions only (edges + feature rows, original ids).
        parts = []
        local_max_e = 0
        for p in local:
            graph, node_feat, _, _, _, _ = load_partition(root, p)
            parts.append((p, graph, node_feat))
            local_max_e = max(local_max_e, int(graph.eids.shape[0]))

        # In-degree hotness (the plain load()'s default) needs incoming
        # edges, which may live in any partition: aggregate local
        # bincounts across hosts.  Pass `hotness` explicitly to skip the
        # O(N * processes) gather at papers100M scale.
        if hotness is None:
            local_deg = np.zeros(num_nodes, np.int64)
            for _, graph, _ in parts:
                local_deg += np.bincount(graph.edge_index[1],
                                         minlength=num_nodes)
            hotness = multihost.agree_sum(local_deg)
        rel = contiguous_relabel(node_pb, hotness=hotness,
                                 num_parts=num_parts)
        c = rel.nodes_per_shard

        # Relabeled per-partition CSR blocks + feature rows.
        part_topos, part_feats = [], []
        feat_dim, feat_dtype = None, None
        for p, graph, node_feat in parts:
            src, dst = graph.edge_index
            nsrc = rel.old2new[src] - p * c
            if nsrc.size and (nsrc.min() < 0 or nsrc.max() >= c):
                raise ValueError(
                    f"partition {p} holds edges whose sources it does not "
                    f"own — not a by_src layout")
            topo_p = CSRTopo(np.stack([nsrc, rel.old2new[dst]]),
                             edge_ids=graph.eids, num_nodes=c)
            part_topos.append(topo_p)
            if node_feat is not None:
                nloc = rel.old2new[node_feat.ids.astype(np.int64)] - p * c
                part_feats.append((nloc, node_feat.feats))
                feat_dim = node_feat.feats.shape[1]
                feat_dtype = node_feat.feats.dtype
            else:
                part_feats.append(None)

        # 2) pad to the globally-agreed edge width; assemble the graph.
        max_e = multihost.agree_max(local_max_e)
        k = len(part_topos)
        ip = np.zeros((k, c + 1), np.int32)
        ix = np.full((k, max_e), -1, np.int32)
        ei = np.full((k, max_e), -1, np.int32)
        for j, t in enumerate(part_topos):
            ne = t.indices.shape[0]
            ip[j] = t.indptr.astype(np.int32)
            ix[j, :ne] = t.indices
            ei[j, :ne] = t.edge_ids
        g = ShardedGraph(
            indptr=multihost.assemble_global(ip, mesh, axis_name),
            indices=multihost.assemble_global(ix, mesh, axis_name),
            edge_ids=multihost.assemble_global(ei, mesh, axis_name),
            nodes_per_shard=c, num_nodes=num_parts * c,
            num_shards=num_parts)

        # 3) features: per-shard [c, d] blocks, hot prefix split per host.
        feature = None
        if feat_dim is not None:
            h = (c if hot_ratio >= 1.0
                 else min(c, max(1, int(round(c * float(hot_ratio))))))
            out_dtype = feat_dtype if dtype is None else np.dtype(dtype)
            hot = np.zeros((k, h, feat_dim), out_dtype)
            cold = np.zeros((num_parts, c - h, feat_dim), feat_dtype)
            for j, pf in enumerate(part_feats):
                if pf is None:
                    continue
                nloc, rows = pf
                blk = np.zeros((c, feat_dim), feat_dtype)
                blk[nloc] = rows
                hot[j] = blk[:h]
                if c > h:
                    cold[local.start + j] = blk[h:]
            hot_arr = multihost.assemble_global(hot, mesh, axis_name)
            if hot_ratio >= 1.0:
                feature = ShardedFeature(rows=hot_arr, nodes_per_shard=c,
                                         num_shards=num_parts)
            else:
                feature = TieredShardedFeature(
                    hot=hot_arr, cold=cold, nodes_per_shard=c,
                    hot_per_shard=h, num_shards=num_parts)

        # 4) labels: whole-graph array (small) -> per-host shard slices.
        lab = None
        if labels is not None:
            lab_new = relabel_rows(np.asarray(labels), rel, fill=-1)
            lab_blk = lab_new.reshape(num_parts, c)[local.start: local.stop]
            lab = multihost.assemble_global(lab_blk, mesh, axis_name)

        return DistDataset(graph=g, feature=feature, labels=lab,
                           relabel=rel, num_parts=num_parts)
