"""Partition directory -> mesh-ready distributed dataset.

Rebuild of the reference's ``distributed/dist_dataset.py:77-164``: there,
``DistDataset.load`` reads one saved partition, merges the hot-feature cache
in front of owned rows (``cat_feature_cache``) and patches the feature
partition book so cached remote rows resolve locally.  The TPU composition
differs where the runtime differs:

* ownership must end up **arithmetic** (``owner = id // c``) for the in-jit
  all-to-all routing, so the partition books are folded into a one-time
  contiguous relabeling (:func:`~glt_tpu.partition.contiguous.contiguous_relabel`)
  instead of being consulted per lookup;
* the hot-cache has no bandwidth to save when exchanges are fixed-shape
  collectives, so hotness instead orders each partition's rows
  hottest-first and selects the **HBM prefix** of a
  :class:`~glt_tpu.parallel.dist_feature.TieredShardedFeature` — the same
  rows the reference would have cached now simply live in fast memory;
* labels ride a sharded ``[S, c]`` block (the reference reads them from a
  whole-graph label file per partition, dist_dataset.py:140-152).

This single-process loader materialises every partition (mirroring the
reference's single-host tests); on a real pod each host would load only its
shards' blocks — the layout already supports that (everything is per-part
files).
"""
from __future__ import annotations

import os
from typing import List, NamedTuple, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..parallel.dist_feature import TieredShardedFeature, shard_feature_tiered
from ..parallel.sharding import (
    ShardedFeature,
    ShardedGraph,
    shard_feature,
    shard_graph,
)
from ..partition.base import load_partition
from ..partition.contiguous import (
    ContiguousRelabel,
    contiguous_relabel,
    relabel_rows,
    relabel_topology,
)
from ..data.topology import CSRTopo


class DistDataset(NamedTuple):
    """Everything the fused distributed train step consumes."""
    graph: ShardedGraph
    feature: Optional[Union[ShardedFeature, TieredShardedFeature]]
    labels: Optional[jnp.ndarray]          # [S, nodes_per_shard], -1 padded
    relabel: ContiguousRelabel
    num_parts: int

    # -- seed handling -----------------------------------------------------
    def translate(self, old_ids: np.ndarray) -> np.ndarray:
        """Global original ids -> relabeled (mesh) ids."""
        return self.relabel.old2new[np.asarray(old_ids)]

    def split_seeds(self, old_ids: np.ndarray, batch_size: int,
                    shuffle: bool = False, seed: int = 0) -> np.ndarray:
        """Group seeds by owner shard into ``[num_batches, S, B]`` (-1 pad).

        The per-rank disjoint seed split of the reference's trainers
        (dist_train_sage_supervised.py:76): shard ``s`` trains on the seeds
        it owns, so hop 0 of every batch needs no exchange.
        """
        new = self.translate(old_ids)
        if shuffle:
            new = new[np.random.default_rng(seed).permutation(new.shape[0])]
        c = self.relabel.nodes_per_shard
        s_count = self.num_parts
        per_shard: List[np.ndarray] = [new[new // c == s]
                                       for s in range(s_count)]
        nb = max((p.shape[0] + batch_size - 1) // batch_size
                 for p in per_shard)
        out = np.full((nb, s_count, batch_size), -1, np.int64)
        for s, ids in enumerate(per_shard):
            for b in range(nb):
                chunk = ids[b * batch_size: (b + 1) * batch_size]
                out[b, s, : chunk.shape[0]] = chunk
        return out

    @staticmethod
    def load(
        root: str,
        hot_ratio: float = 1.0,
        labels: Optional[np.ndarray] = None,
        hotness: Optional[np.ndarray] = None,
        dtype=None,
    ) -> "DistDataset":
        """Compose a saved partition dir into mesh-ready sharded arrays.

        Args:
          root: partitioner output directory (any PartitionerBase subclass
            or DistRandomPartitioner layout).
          hot_ratio: fraction of each shard's rows resident in HBM
            (1.0 = plain :class:`ShardedFeature`, no host tier).
          labels: optional global ``[N]`` label array (the reference's
            whole-graph label file).
          hotness: optional global ``[N]`` score ordering each partition's
            rows hottest-first; defaults to in-degree
            (``sort_by_in_degree``, reference data/reorder.py:18).
        """
        import json

        with open(os.path.join(root, "META.json")) as fh:
            meta = json.load(fh)
        num_parts = int(meta["num_parts"])
        num_nodes = int(meta["num_nodes"])
        node_pb = np.load(os.path.join(root, "node_pb.npy"))

        # 1) gather every partition's edges + features (single-process
        #    emulation; per-host loads on a real pod).
        edge_chunks, eid_chunks = [], []
        feat_ids, feat_rows = [], []
        feat_dim = None
        for p in range(num_parts):
            graph, node_feat, _, _, _, _ = load_partition(root, p)
            edge_chunks.append(graph.edge_index)
            eid_chunks.append(graph.eids)
            if node_feat is not None:
                feat_ids.append(node_feat.ids)
                feat_rows.append(node_feat.feats)
                feat_dim = node_feat.feats.shape[1]
        edge_index = np.concatenate(edge_chunks, axis=1)
        edge_ids = np.concatenate(eid_chunks)

        # 2) hotness-ordered contiguous relabel (the cat_feature_cache
        #    analog — see module docstring).
        if hotness is None:
            hotness = np.bincount(edge_index[1], minlength=num_nodes)
        rel = contiguous_relabel(node_pb, hotness=hotness,
                                 num_parts=num_parts)

        topo = relabel_topology(
            CSRTopo(edge_index, edge_ids=edge_ids, num_nodes=num_nodes), rel)
        g = shard_graph(topo, num_parts)

        # 3) features into new-id order, then tier/shard.
        feature = None
        if feat_dim is not None:
            all_ids = np.concatenate(feat_ids)
            all_rows = np.concatenate(feat_rows)
            full = np.zeros((num_nodes, feat_dim), all_rows.dtype)
            full[all_ids.astype(np.int64)] = all_rows
            new_order = relabel_rows(full, rel)
            if hot_ratio >= 1.0:
                feature = shard_feature(new_order, num_parts, dtype=dtype)
            else:
                feature = shard_feature_tiered(new_order, num_parts,
                                               hot_ratio, dtype=dtype)

        lab = None
        if labels is not None:
            lab_new = relabel_rows(np.asarray(labels), rel, fill=-1)
            lab = jnp.asarray(
                lab_new.reshape(num_parts, rel.nodes_per_shard))

        return DistDataset(graph=g, feature=feature, labels=lab,
                           relabel=rel, num_parts=num_parts)
