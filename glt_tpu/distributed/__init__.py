from .dist_options import (
    CollocatedSamplingWorkerOptions,
    MpSamplingWorkerOptions,
    RemoteSamplingWorkerOptions,
)
from .dist_context import (
    DistContext,
    DistRole,
    get_context,
    init_client_context,
    init_server_context,
    init_worker_group,
)
from .dist_client import (
    RemoteNeighborLoader,
    RemoteServerConnection,
    UnknownProducerError,
)
from .dist_dataset import DistDataset
from .dist_loader import (
    DistHeteroNeighborLoader,
    DistLinkNeighborLoader,
    DistNeighborLoader,
    DistSubGraphLoader,
)
from .dist_server import DistServer, ProtocolError, init_server
from .sample_message import batch_to_message, message_to_batch

__all__ = [
    "CollocatedSamplingWorkerOptions",
    "DistContext",
    "DistDataset",
    "DistHeteroNeighborLoader",
    "DistRole",
    "DistServer",
    "get_context",
    "init_client_context",
    "init_server",
    "init_server_context",
    "init_worker_group",
    "DistLinkNeighborLoader",
    "DistNeighborLoader",
    "DistSubGraphLoader",
    "MpSamplingWorkerOptions",
    "ProtocolError",
    "RemoteNeighborLoader",
    "RemoteSamplingWorkerOptions",
    "RemoteServerConnection",
    "UnknownProducerError",
    "batch_to_message",
    "message_to_batch",
]
