from .dist_options import (
    CollocatedSamplingWorkerOptions,
    MpSamplingWorkerOptions,
    RemoteSamplingWorkerOptions,
)
from .dist_dataset import DistDataset
from .dist_loader import (
    DistLinkNeighborLoader,
    DistNeighborLoader,
    DistSubGraphLoader,
)
from .sample_message import batch_to_message, message_to_batch

__all__ = [
    "CollocatedSamplingWorkerOptions",
    "DistDataset",
    "DistLinkNeighborLoader",
    "DistNeighborLoader",
    "DistSubGraphLoader",
    "MpSamplingWorkerOptions",
    "RemoteSamplingWorkerOptions",
    "batch_to_message",
    "message_to_batch",
]
