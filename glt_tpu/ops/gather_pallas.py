"""Pallas row-gather kernels: the feature-lookup hot op.

TPU counterpart of the reference's ``GatherTensorKernel``
(csrc/cuda/unified_tensor.cu:48-81): there, one warp copies each requested
row from GPU/peer/pinned-host memory.

Two generations of kernel live here:

* **round 3 (retired design, kept as the lesson):** one async DMA per
  requested row, ``_LAG``-deep pipelined.  Measured honestly (device-synced
  timing) XLA's native gather beat it ~2x at 512B rows (4.6 vs 9.8 ms per
  102400-row gather on the v5-lite chip): per-row DMAs are **issue-rate
  bound**, not bandwidth bound — the bench's ``est_hbm_fraction`` of 0.0005
  says the gather path moves <0.1% of HBM peak, so issuing the same number
  of DMAs faster was never going to win.

* **tiled (current):** the win is in **coalescing**, not issue rate.  The
  index list is sorted (XLA prologue), mapped onto aligned ``_TILE``-row
  blocks of the table, and each *distinct* block is fetched with ONE
  block DMA into a ``_NBUF``-deep ring of VMEM tile buffers (double
  buffering generalised to ``_NBUF`` slots, ``_NBUF - 1`` DMAs in flight
  while rows of the current tile are copied out).  Rows are emitted in
  sorted order and un-permuted by an XLA epilogue gather.  Hotness-ordered
  feature stores (:func:`~glt_tpu.data.reorder.sort_by_in_degree`) cluster
  a batch's unique ids near the head of the table, so sorted runs share
  tiles and one 4-16KB DMA serves many rows — the DMA count drops by the
  clustering factor and each DMA is deep enough to stream.

``gather_rows(force='auto')`` stays the A/B seam: it consults a per-(row
width, batch, dtype) decision table filled by :func:`autotune_gather_rows`
at warmup (eager, fetch-synced timing — ``block_until_ready`` lies under
the axon tunnel, see bench.py) and falls back to XLA's gather wherever the
kernel's shape constraints don't hold or no measurement exists.
"""
from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE = 8     # table rows per block DMA (8 x 512B = 4KB at d=128 f32)
_CHUNK = 256  # output rows per grid step
_NBUF = 8     # VMEM tile buffers == max DMAs in flight

# Decision table for force='auto': (d, b, dtype) -> 'xla' | 'pallas',
# filled by autotune_gather_rows (eager warmup only — a traced call can
# not time anything, it just reads this table).
_AUTO: dict = {}


def _plan_tiled(idx: jnp.ndarray, n: int):
    """XLA prologue: sort ids and coalesce them into aligned tile DMAs.

    Returns static-shape descriptor arrays for :func:`gather_rows_pallas`:
      order     [B]  sorted position -> original position
      dstart    [G, _CHUNK] first table row of each DMA (-chunk-local slot)
      row_lo/hi [G, _CHUNK] chunk-relative sorted-row range served per DMA
      ndma      [G]  live DMA count per chunk
      off       [B]  row offset of each sorted row inside its tile
    """
    b = idx.shape[0]
    nchunk = b // _CHUNK
    idx = jnp.clip(idx.astype(jnp.int32), 0, n - 1)
    order = jnp.argsort(idx, stable=True)
    sidx = idx[order]
    # Aligned tiles, clamped so the block DMA never overruns the table.
    dstart_row = jnp.clip((sidx // _TILE) * _TILE, 0, n - _TILE)
    off = (sidx - dstart_row).astype(jnp.int32)

    r = jnp.arange(b, dtype=jnp.int32)
    rel = r % _CHUNK
    chunk = r // _CHUNK
    prev = jnp.concatenate(
        [jnp.full((1,), -1, dstart_row.dtype), dstart_row[:-1]])
    # A new DMA starts at every distinct tile and at every chunk boundary
    # (a tile straddling two chunks is fetched once per chunk).
    head = (dstart_row != prev) | (rel == 0)
    gidx = jnp.cumsum(head.astype(jnp.int32)) - 1
    first = gidx[0::_CHUNK]                       # [G]
    dma_j = gidx - first[chunk]                   # [B], in [0, _CHUNK)
    ndma = gidx[_CHUNK - 1::_CHUNK] - first + 1   # [G]

    # Scatter per-DMA descriptors; non-head rows land in an overflow
    # column that is sliced off.
    col = jnp.where(head, dma_j, _CHUNK)
    dstart = (jnp.zeros((nchunk, _CHUNK + 1), jnp.int32)
              .at[chunk, col].set(dstart_row)[:, :_CHUNK])
    row_lo = (jnp.full((nchunk, _CHUNK + 1), _CHUNK, jnp.int32)
              .at[chunk, col].set(rel)[:, :_CHUNK])
    row_hi = jnp.concatenate(
        [row_lo[:, 1:], jnp.full((nchunk, 1), _CHUNK, jnp.int32)], axis=1)
    return order, dstart, row_lo, row_hi, ndma, off


def _tiled_kernel(dstart_ref, row_lo_ref, row_hi_ref, ndma_ref, off_ref,
                  table_ref, out_ref, tiles, sems):
    c = pl.program_id(0)
    nd = ndma_ref[c]

    def dma(j):
        slot = lax.rem(j, _NBUF)
        start = dstart_ref[c, j]
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(start, _TILE)], tiles.at[slot],
            sems.at[slot])

    # Fill the pipeline: up to _NBUF block DMAs in flight.
    for k in range(_NBUF):
        @pl.when(k < nd)
        def _():
            dma(k).start()

    def body(j, _):
        slot = lax.rem(j, _NBUF)
        dma(j).wait()
        lo = row_lo_ref[c, j]
        hi = row_hi_ref[c, j]

        def copy_row(s, _):
            o = off_ref[c * _CHUNK + s]
            row = pl.load(tiles, (slot, pl.ds(o, 1), slice(None)))
            pl.store(out_ref, (pl.ds(s, 1), slice(None)), row)
            return _

        lax.fori_loop(lo, hi, copy_row, None)
        # Only after this tile's rows are consumed may its buffer slot be
        # reissued (slot j % _NBUF == slot (j + _NBUF) % _NBUF).
        @pl.when(j + _NBUF < nd)
        def _():
            dma(j + _NBUF).start()
        return _

    lax.fori_loop(0, nd, body, None)


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_rows_pallas(table: jnp.ndarray, idx: jnp.ndarray,
                       interpret: bool = False) -> jnp.ndarray:
    """Gather ``table[idx]`` via coalesced block DMAs.

    Args:
      table: ``[N, d]`` feature matrix (HBM-resident), ``N >= 8``,
        ``d % 128 == 0``.
      idx: ``[B]`` int32 row ids; out-of-range/negative ids are clamped
        (callers mask padding rows).  ``B`` is padded internally to a
        multiple of 256.
    """
    b = idx.shape[0]
    n, d = table.shape
    if d % 128 != 0:
        raise ValueError(f"dim {d} must be a multiple of 128")
    if n < _TILE:
        raise ValueError(f"table rows {n} must be >= {_TILE}")
    bp = -(-b // _CHUNK) * _CHUNK
    idx_p = jnp.concatenate(
        [idx.astype(jnp.int32), jnp.zeros((bp - b,), jnp.int32)])

    order, dstart, row_lo, row_hi, ndma, off = _plan_tiled(idx_p, n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(bp // _CHUNK,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((_CHUNK, d), lambda c, *_: (c, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((_NBUF, _TILE, d), table.dtype),
            pltpu.SemaphoreType.DMA((_NBUF,)),
        ],
    )
    sorted_out = pl.pallas_call(
        _tiled_kernel,
        out_shape=jax.ShapeDtypeStruct((bp, d), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(dstart, row_lo, row_hi, ndma, off, table)

    # Un-permute: sorted row k belongs at original position order[k].
    inv = (jnp.zeros((bp,), jnp.int32)
           .at[order].set(jnp.arange(bp, dtype=jnp.int32)))
    return jnp.take(sorted_out, inv[:b], axis=0)


def _xla_gather(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    return jnp.take(table, jnp.clip(idx, 0, table.shape[0] - 1), axis=0)


def pallas_gather_supported(table, idx) -> bool:
    """Shape constraints of the tiled kernel (dtype-agnostic)."""
    return table.shape[1] % 128 == 0 and table.shape[0] >= _TILE


def _auto_key(table, idx):
    return (int(table.shape[1]), int(idx.shape[0]), str(table.dtype))


def autotune_gather_rows(table: jnp.ndarray, idx: jnp.ndarray,
                         iters: int = 3) -> str:
    """Measure XLA vs the tiled kernel for this (row width, batch, dtype)
    and memoize the winner for ``gather_rows(force='auto')``.

    Call EAGERLY at warmup (loader construction / bench setup) — never
    from inside a trace.  Timing is fetch-synced (a host scalar fetch is
    the only sync that provably waits under the axon tunnel; see
    bench.py).  Off-TPU backends and unsupported shapes pin 'xla'.
    """
    key = _auto_key(table, idx)
    if key in _AUTO:
        return _AUTO[key]
    choice = "xla"
    if (jax.default_backend() == "tpu"
            and pallas_gather_supported(table, idx)):
        try:
            def timed(fn):
                float(fn(table, idx)[0, 0])  # compile + warm
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = fn(table, idx)
                float(out[0, 0])             # fetch = true sync
                return time.perf_counter() - t0

            t_xla = timed(_xla_gather)
            t_pal = timed(gather_rows_pallas)
            choice = "pallas" if t_pal < t_xla else "xla"
        except Exception:  # pragma: no cover - kernel unsupported on chip
            choice = "xla"
    _AUTO[key] = choice
    # Autotune runs host-side at warmup (never under trace — GLT010), so
    # the kernel decision is safe to publish here.
    from ..obs import metrics as _metrics

    _metrics.counter("glt.gather.autotune_runs",
                     "gather kernel A/B warmups").inc()
    _metrics.gauge("glt.gather.pallas_selected",
                   "1 if the last gather autotune picked the tiled "
                   "Pallas kernel", labels={"d": str(key[0])},
                   ).set(1.0 if choice == "pallas" else 0.0)
    return choice


def gather_rows(table: jnp.ndarray, idx: jnp.ndarray,
                force: str = "auto") -> jnp.ndarray:
    """Gather rows, choosing the best implementation.

    force: 'auto' | 'pallas' | 'xla'.  'auto' reads the decision table
    filled by :func:`autotune_gather_rows` (XLA until a measurement
    exists).  The ``GLT_GATHER_FORCE`` env var overrides ``force``.
    """
    env = os.environ.get("GLT_GATHER_FORCE")
    if env in ("pallas", "xla"):
        force = env
    if force == "pallas":
        return gather_rows_pallas(table, idx)
    if force == "auto" and _AUTO.get(_auto_key(table, idx)) == "pallas":
        return gather_rows_pallas(table, idx)
    return _xla_gather(table, idx)
