"""Pallas row-gather kernel: the feature-lookup hot op.

TPU counterpart of the reference's ``GatherTensorKernel``
(csrc/cuda/unified_tensor.cu:48-81): there, one warp copies each requested
row from GPU/peer/pinned-host memory.  Here each grid step issues
per-row **async DMAs from HBM into the VMEM output block** with the index
list scalar-prefetched into SMEM (so row addresses are known before the
body runs), overlapping up to ``LAG`` row copies — the DMA-pipelined
equivalent of the warp-per-row design.

**Measured honestly (round 3, device-synced timing), XLA's native gather
beats this kernel ~2x at 512B rows** (4.6 vs 9.8 ms per 102400-row
gather on the v5-lite chip): the per-row DMA issue rate, even with
``_LAG``-deep pipelining, loses to the hardware gather unit.  Round 1's
"+15%" for this kernel was an artifact of ``block_until_ready`` not
actually waiting under the axon tunnel (see bench.py).  ``gather_rows``
therefore defaults to ``jnp.take``; the kernel stays available via
``force='pallas'`` as the seam for future multi-stream DMA work.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Rows in flight per grid step; also the semaphore-array width.
_LAG = 8
_CHUNK = 256  # rows per grid step


def _gather_kernel(idx_ref, table_ref, out_ref, sems):
    i = pl.program_id(0)
    n = table_ref.shape[0]

    def row_dma(r):
        gid = idx_ref[i * _CHUNK + r]
        gid = jnp.clip(gid, 0, n - 1)
        return pltpu.make_async_copy(
            table_ref.at[gid], out_ref.at[r], sems.at[r % _LAG])

    def body(r, _):
        # Wait for the DMA LAG rows back (same semaphore slot) before
        # reusing its semaphore for row r.
        @pl.when(r >= _LAG)
        def _():
            row_dma(r - _LAG).wait()
        row_dma(r).start()
        return _

    lax.fori_loop(0, _CHUNK, body, None)

    def drain(r, _):
        row_dma(r).wait()
        return _

    lax.fori_loop(_CHUNK - _LAG, _CHUNK, drain, None)


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_rows_pallas(table: jnp.ndarray, idx: jnp.ndarray,
                       interpret: bool = False) -> jnp.ndarray:
    """Gather ``table[idx]`` via DMA pipelining.

    Args:
      table: ``[N, d]`` feature matrix (HBM-resident).
      idx: ``[B]`` int32 row ids; out-of-range/negative ids are clamped
        (callers mask padding rows).
    Requires ``B % 256 == 0`` and ``d % 128 == 0`` (pad first).
    """
    b = idx.shape[0]
    d = table.shape[1]
    if b % _CHUNK != 0:
        raise ValueError(f"batch {b} must be a multiple of {_CHUNK}")
    if d % 128 != 0:
        raise ValueError(f"dim {d} must be a multiple of 128")

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b // _CHUNK,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((_CHUNK, d), lambda i, idx_ref: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_LAG,))],
    )
    return pl.pallas_call(
        _gather_kernel,
        out_shape=jax.ShapeDtypeStruct((b, d), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx.astype(jnp.int32), table)


def gather_rows(table: jnp.ndarray, idx: jnp.ndarray,
                force: str = "auto") -> jnp.ndarray:
    """Gather rows, choosing the best implementation.

    force: 'auto' | 'pallas' | 'xla'.
    """
    # 'auto' = XLA take: measured 2x faster than the DMA kernel at 512B
    # rows with honest device-synced timing (module docstring).
    if force == "pallas":
        return gather_rows_pallas(table, idx)
    return jnp.take(table, jnp.clip(idx, 0, table.shape[0] - 1), axis=0)
