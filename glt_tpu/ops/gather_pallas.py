"""Pallas row-gather kernels: the feature-lookup hot op.

TPU counterpart of the reference's ``GatherTensorKernel``
(csrc/cuda/unified_tensor.cu:48-81): there, one warp copies each requested
row from GPU/peer/pinned-host memory.

Three generations of kernel live here (two as lessons, one current):

* **round 3 (retired):** one async DMA per requested row, pipelined.
  Measured honestly, XLA's native gather beat it ~2x at 512B rows:
  per-row DMAs are **issue-rate bound**, not bandwidth bound.

* **round 5 (superseded):** fixed 8-row tiles, fixed 8-slot ring.  It
  coalesced sorted runs into block DMAs, but every DMA was 4KB at d=128
  — deep enough to beat per-row issue, far too shallow to stream: the
  bench read 0.05% of HBM bandwidth and ``gather_ms`` stayed the
  dominant step cost (BENCH_r05: 81 ms vs 36.5 ms train).

* **tiled, parameterized (current):** the same sorted-run coalescing,
  but the two knobs that set DMA depth and overlap are now free
  parameters swept by the autotuner:

    - ``tile_rows`` — table rows per block DMA.  Bigger tiles amortize
      DMA setup and stream deeper; the width-specialized defaults hold
      the DMA *byte* depth roughly constant (~16KB) across row widths,
      so d=64 tables use 32-row tiles where d=256 uses 16.
    - ``ring_depth`` — VMEM tile slots == DMAs in flight.  The copy
      ring is double-buffered in the general sense: while rows of tile
      ``j`` are copied out to the output block, the DMAs for tiles
      ``j+1 .. j+ring_depth-1`` are already streaming.

  Width specialization also covers **d=64** (the common "half-lane"
  embedding width): the table is viewed as ``[N/2, 128]`` paired rows,
  the kernel moves full 128-lane rows (the lanes a 64-wide DMA would
  pad to anyway), and an XLA epilogue selects the requested half.

``gather_rows(force='auto')`` stays the A/B seam: it consults a
per-(row width, batch, dtype) decision table filled by
:func:`autotune_gather_rows` at warmup (eager, fetch-synced timing —
``block_until_ready`` lies under the axon tunnel, see bench.py).  The
autotuner now sweeps the (tile_rows, ring_depth) grid per shape and
memoizes the winning *parameters*, not just the kernel choice; the
full sweep table is exported (:func:`autotune_table`) so bench.py can
publish the per-(width, tile, ring) landscape.  Because the table is
keyed by the exact batch size, an occupancy-capped loader shape gets
its own sweep instead of inheriting the full-cap winner (the
BENCH_r05 ``gather_ms_capped`` > ``gather_ms`` inversion).
"""
from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tpu_limits
from ..store import quant

_CHUNK = 256  # output rows per grid step (batch padded to a multiple)
_LANE = tpu_limits.LANE
_MIN_TILE = tpu_limits.SUBLANE_F32
# Sublane count of the packed scale/zero input block (== quant.
# SCALE_ZERO_ROWS): row 0 = scale, row 1 = zero, padded to the f32
# tiling floor so the block satisfies GLT019.
_SZ_ROWS = 8

# The (tile_rows, ring_depth) grid the autotuner sweeps — and the grid
# the static VMEM model (analysis/kernelmodel.py GLT017) verifies every
# point of, via VMEM_MODEL_DOMAIN below.
CANDIDATE_TILE_ROWS = (8, 16, 32)
CANDIDATE_RING_DEPTHS = (4, 8)

# Dimension domain for the static VMEM model: analysis/kernelmodel.py
# resolves this dict through the symbol table and checks the closed-form
# VMEM accounting of _gather_sorted_pallas at EVERY assignment of these
# symbols against tpu_limits.VMEM_BYTES.  tile_rows/ring_depth are the
# sweep axes (same tuples the autotuner crosses); `d` is the widest
# feature row the kernel is modeled at.
VMEM_MODEL_DOMAIN = {
    "tile_rows": CANDIDATE_TILE_ROWS,
    "ring_depth": CANDIDATE_RING_DEPTHS,
    "d": tpu_limits.MODEL_MAX_LANES,
}

# Decision table for force='auto': (d, b, dtype) ->
#   ("xla", None) | ("pallas", (tile_rows, ring_depth)).
# Filled by autotune_gather_rows (eager warmup only — a traced call can
# not time anything, it just reads this table).
_AUTO: dict = {}
# Per-key sweep timings for the bench's autotune table:
# (d, b, dtype) -> {"xla": ms, "t8_r4": ms, ...}.
_AUTO_TIMES: dict = {}


def _sublane_min(dtype) -> int:
    """Smallest legal second-to-last tile dim for this dtype (f32 8,
    bf16 16, int8/fp8 32 — pallas_guide.md 'Tiling Constraints')."""
    return tpu_limits.sublane_min(jnp.dtype(dtype).itemsize)


def default_gather_params(d: int, dtype=jnp.float32) -> tuple:
    """Width-specialized (tile_rows, ring_depth) defaults.

    Holds DMA depth near 16KB per block across row widths — the depth
    where a v5-class DMA engine streams instead of paying setup per
    transfer — and keeps enough ring slots for ~2 tiles of copy-out
    latency to hide behind in-flight DMAs.
    """
    row_bytes = max(int(d) * jnp.dtype(dtype).itemsize, 1)
    tile = max(_sublane_min(dtype),
               min(32, tpu_limits.DMA_DEPTH_TARGET_BYTES // row_bytes))
    tile = max(_MIN_TILE, (tile // _MIN_TILE) * _MIN_TILE)
    return tile, 8


def candidate_gather_params(d: int, dtype=jnp.float32) -> list:
    """The (tile_rows, ring_depth) grid :func:`autotune_gather_rows`
    sweeps for one shape.  Small by design: 3 tile depths x 2 ring
    depths, pruned to legal sublane multiples for the dtype."""
    lo = _sublane_min(dtype)
    tiles = sorted({t for t in CANDIDATE_TILE_ROWS if t >= lo})
    return [(t, r) for t in tiles for r in CANDIDATE_RING_DEPTHS]


def _plan_tiled(idx: jnp.ndarray, n: int, tile: int):
    """XLA prologue: sort ids and coalesce them into aligned tile DMAs.

    Returns static-shape descriptor arrays for the kernel:
      order     [B]  sorted position -> original position
      dstart    [G, _CHUNK] first table row of each DMA
      row_lo/hi [G, _CHUNK] chunk-relative sorted-row range served per DMA
      ndma      [G]  live DMA count per chunk
      off       [B]  row offset of each sorted row inside its tile
    """
    b = idx.shape[0]
    nchunk = b // _CHUNK
    idx = jnp.clip(idx.astype(jnp.int32), 0, n - 1)
    order = jnp.argsort(idx, stable=True)
    sidx = idx[order]
    # Aligned tiles, clamped so the block DMA never overruns the table.
    dstart_row = jnp.clip((sidx // tile) * tile, 0, n - tile)
    off = (sidx - dstart_row).astype(jnp.int32)

    r = jnp.arange(b, dtype=jnp.int32)
    rel = r % _CHUNK
    chunk = r // _CHUNK
    prev = jnp.concatenate(
        [jnp.full((1,), -1, dstart_row.dtype), dstart_row[:-1]])
    # A new DMA starts at every distinct tile and at every chunk boundary
    # (a tile straddling two chunks is fetched once per chunk).
    head = (dstart_row != prev) | (rel == 0)
    gidx = jnp.cumsum(head.astype(jnp.int32)) - 1
    first = gidx[0::_CHUNK]                       # [G]
    dma_j = gidx - first[chunk]                   # [B], in [0, _CHUNK)
    ndma = gidx[_CHUNK - 1::_CHUNK] - first + 1   # [G]

    # Scatter per-DMA descriptors; non-head rows land in an overflow
    # column that is sliced off.
    col = jnp.where(head, dma_j, _CHUNK)
    dstart = (jnp.zeros((nchunk, _CHUNK + 1), jnp.int32)
              .at[chunk, col].set(dstart_row)[:, :_CHUNK])
    row_lo = (jnp.full((nchunk, _CHUNK + 1), _CHUNK, jnp.int32)
              .at[chunk, col].set(rel)[:, :_CHUNK])
    row_hi = jnp.concatenate(
        [row_lo[:, 1:], jnp.full((nchunk, 1), _CHUNK, jnp.int32)], axis=1)
    return order, dstart, row_lo, row_hi, ndma, off


def _make_tiled_kernel(tile: int, nbuf: int):
    """Kernel body over a (tile_rows, ring_depth) parameter point."""

    def kernel(dstart_ref, row_lo_ref, row_hi_ref, ndma_ref, off_ref,
               table_ref, out_ref, tiles, sems):
        c = pl.program_id(0)
        nd = ndma_ref[c]

        def dma(j):
            slot = lax.rem(j, nbuf)
            start = dstart_ref[c, j]
            return pltpu.make_async_copy(
                table_ref.at[pl.ds(start, tile)], tiles.at[slot],
                sems.at[slot])

        # Fill the ring: up to `nbuf` block DMAs in flight before the
        # first copy-out touches a buffer.
        for k in range(nbuf):
            @pl.when(k < nd)
            def _():
                dma(k).start()

        def body(j, _):
            slot = lax.rem(j, nbuf)
            dma(j).wait()
            lo = row_lo_ref[c, j]
            hi = row_hi_ref[c, j]

            def copy_row(s, _):
                o = off_ref[c * _CHUNK + s]
                row = pl.load(tiles, (slot, pl.ds(o, 1), slice(None)))
                pl.store(out_ref, (pl.ds(s, 1), slice(None)), row)
                return _

            lax.fori_loop(lo, hi, copy_row, None)
            # Only after this tile's rows are consumed may its buffer
            # slot be reissued (slot j % nbuf == slot (j + nbuf) % nbuf):
            # the next tile's DMA streams while later tiles copy out.
            @pl.when(j + nbuf < nd)
            def _():
                dma(j + nbuf).start()
            return _

        lax.fori_loop(0, nd, body, None)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("interpret", "tile_rows", "ring_depth"))
def _gather_sorted_pallas(table, idx_p, interpret, tile_rows, ring_depth):
    """Core call: gather clip(idx_p) from a lane-aligned table.

    ``idx_p`` is already padded to a _CHUNK multiple; returns rows in
    the ORIGINAL (unsorted) order.  ``table`` last dim must be a
    multiple of 128.
    """
    bp = idx_p.shape[0]
    n, d = table.shape
    order, dstart, row_lo, row_hi, ndma, off = _plan_tiled(
        idx_p, n, tile_rows)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(bp // _CHUNK,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((_CHUNK, d), lambda c, *_: (c, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((ring_depth, tile_rows, d), table.dtype),
            pltpu.SemaphoreType.DMA((ring_depth,)),
        ],
    )
    sorted_out = pl.pallas_call(
        _make_tiled_kernel(tile_rows, ring_depth),
        out_shape=jax.ShapeDtypeStruct((bp, d), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(dstart, row_lo, row_hi, ndma, off, table)

    # Un-permute: sorted row k belongs at original position order[k].
    inv = (jnp.zeros((bp,), jnp.int32)
           .at[order].set(jnp.arange(bp, dtype=jnp.int32)))
    return jnp.take(sorted_out, inv, axis=0)


def _make_tiled_dequant_kernel(tile: int, nbuf: int, mode: str):
    """The tiled gather kernel with a dequantize epilogue on copy-out.

    Identical DMA structure to :func:`_make_tiled_kernel` — compressed
    table rows stream HBM->VMEM at their narrow storage width and widen
    to f32 only as each row is copied to the output block, so the DMA
    ring moves 2x (bf16) / 4x (int8) fewer bytes than a raw f32 gather.

    ``mode`` is static: ``"widen"`` is a plain f32 astype (bf16 —
    deliberately NOT ``x * 1 + 0``, which would flip ``-0.0``);
    ``"affine"`` applies the per-column ``(x + k) * scale`` /
    constant-column select from the ``sz`` input block (row 0 = scale,
    row 1 = zero, row 2 = k).  The formulas mirror :func:`glt_tpu.
    store.quant.dequantize` exactly — add-then-mul is
    contraction-proof (quant module docstring), so the XLA arm of the
    seam agrees bit-for-bit.
    """

    def kernel(dstart_ref, row_lo_ref, row_hi_ref, ndma_ref, off_ref,
               table_ref, sz_ref, out_ref, tiles, sems):
        c = pl.program_id(0)
        nd = ndma_ref[c]
        scale = sz_ref[0:1, :]
        zero = sz_ref[1:2, :]
        kvec = sz_ref[2:3, :]

        def dma(j):
            slot = lax.rem(j, nbuf)
            start = dstart_ref[c, j]
            return pltpu.make_async_copy(
                table_ref.at[pl.ds(start, tile)], tiles.at[slot],
                sems.at[slot])

        for k in range(nbuf):
            @pl.when(k < nd)
            def _():
                dma(k).start()

        def body(j, _):
            slot = lax.rem(j, nbuf)
            dma(j).wait()
            lo = row_lo_ref[c, j]
            hi = row_hi_ref[c, j]

            def copy_row(s, _):
                o = off_ref[c * _CHUNK + s]
                row = pl.load(tiles, (slot, pl.ds(o, 1), slice(None)))
                row = row.astype(jnp.float32)
                if mode == "affine":
                    row = jnp.where(scale > 0.0, (row + kvec) * scale,
                                    zero)
                pl.store(out_ref, (pl.ds(s, 1), slice(None)), row)
                return _

            lax.fori_loop(lo, hi, copy_row, None)
            @pl.when(j + nbuf < nd)
            def _():
                dma(j + nbuf).start()
            return _

        lax.fori_loop(0, nd, body, None)

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret", "tile_rows",
                                             "ring_depth", "mode"))
def _gather_sorted_pallas_dq(table, sz, idx_p, interpret, tile_rows,
                             ring_depth, mode):
    """Dequantizing twin of :func:`_gather_sorted_pallas`: compressed
    ``table`` in, f32 rows out.  ``sz`` is the ``[_SZ_ROWS, d]`` f32
    scale/zero block (:func:`glt_tpu.store.quant.scale_zero_rows`)."""
    bp = idx_p.shape[0]
    n, d = table.shape
    order, dstart, row_lo, row_hi, ndma, off = _plan_tiled(
        idx_p, n, tile_rows)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(bp // _CHUNK,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((_SZ_ROWS, d), lambda c, *_: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_CHUNK, d), lambda c, *_: (c, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((ring_depth, tile_rows, d), table.dtype),
            pltpu.SemaphoreType.DMA((ring_depth,)),
        ],
    )
    sorted_out = pl.pallas_call(
        _make_tiled_dequant_kernel(tile_rows, ring_depth, mode),
        out_shape=jax.ShapeDtypeStruct((bp, d), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(dstart, row_lo, row_hi, ndma, off, table, sz)

    inv = (jnp.zeros((bp,), jnp.int32)
           .at[order].set(jnp.arange(bp, dtype=jnp.int32)))
    return jnp.take(sorted_out, inv, axis=0)


def gather_rows_pallas_dq(table: jnp.ndarray, idx: jnp.ndarray,
                          spec, interpret: bool = False,
                          tile_rows: int = None,
                          ring_depth: int = None) -> jnp.ndarray:
    """Gather compressed ``table[idx]`` and dequantize on-chip to f32.

    Same shape contract as :func:`gather_rows_pallas`; ``spec`` is the
    store's :class:`~glt_tpu.store.quant.QuantSpec`.  int8 tables obey
    the 32-sublane tiling floor through the same
    :func:`candidate_gather_params` pruning as any 1-byte dtype.
    """
    b = idx.shape[0]
    n, d = table.shape
    mode = "affine" if spec.codec == "int8" else "widen"
    if tile_rows is None or ring_depth is None:
        dt, dr = default_gather_params(d if d % _LANE == 0 else 128,
                                       table.dtype)
        if tile_rows is None:
            rows = n if d % _LANE == 0 else n // 2
            lo = _sublane_min(table.dtype)
            tile_rows = max(lo, min(dt, (rows // lo) * lo))
        if ring_depth is None:
            ring_depth = dr
    bp = -(-b // _CHUNK) * _CHUNK
    idx_p = jnp.concatenate(
        [idx.astype(jnp.int32), jnp.zeros((bp - b,), jnp.int32)])

    if d % _LANE == 0:
        if n < tile_rows:
            raise ValueError(f"table rows {n} must be >= {tile_rows}")
        sz = jnp.asarray(quant.scale_zero_rows(spec, d))
        out = _gather_sorted_pallas_dq(table, sz, idx_p, interpret,
                                       tile_rows, ring_depth, mode)
        return out[:b]
    if d == 64:
        # Paired-row view, as in gather_rows_pallas.  Column j of the
        # original table lands in lanes j AND 64 + j of the paired
        # view, so scale/zero are tiled twice along lanes; dequant runs
        # on the full 128-lane row BEFORE the half-select (the same
        # per-element formula either side of the select).
        if n % 2 != 0:
            raise ValueError(f"d=64 path needs an even row count, got {n}")
        if n // 2 < tile_rows:
            raise ValueError(
                f"paired table rows {n // 2} must be >= {tile_rows}")
        idx_c = jnp.clip(idx_p, 0, n - 1)
        sz64 = quant.scale_zero_rows(spec, 64)
        sz = jnp.asarray(
            jnp.concatenate([jnp.asarray(sz64), jnp.asarray(sz64)], axis=1))
        paired = _gather_sorted_pallas_dq(
            table.reshape(n // 2, _LANE), sz, idx_c // 2, interpret,
            tile_rows, ring_depth, mode)
        half = jnp.take_along_axis(
            paired.reshape(bp, 2, 64),
            (idx_c % 2)[:, None, None], axis=1)[:, 0]
        return half[:b]
    raise ValueError(f"dim {d} must be a multiple of 128 (or exactly 64)")


def gather_rows_pallas(table: jnp.ndarray, idx: jnp.ndarray,
                       interpret: bool = False,
                       tile_rows: int = None,
                       ring_depth: int = None) -> jnp.ndarray:
    """Gather ``table[idx]`` via coalesced block DMAs.

    Args:
      table: ``[N, d]`` feature matrix (HBM-resident).  ``d % _LANE == 0``
        runs natively; ``d == 64`` runs through the paired-row view
        (``N`` must be even); other widths raise.  ``N >= tile_rows``.
      idx: ``[B]`` int32 row ids; out-of-range/negative ids are clamped
        (callers mask padding rows).  ``B`` is padded internally to a
        multiple of 256.
      tile_rows / ring_depth: DMA tile depth and copy-ring slots; None
        picks the width-specialized default
        (:func:`default_gather_params`).
    """
    b = idx.shape[0]
    n, d = table.shape
    # NOTE: tile_rows/ring_depth are static Python ints (jit static
    # args) — no coercions here, so the transitive host-sync analysis
    # (GLT001) sees this body as jnp-pure from every traced caller.
    if tile_rows is None or ring_depth is None:
        dt, dr = default_gather_params(d if d % _LANE == 0 else 128,
                                       table.dtype)
        if tile_rows is None:
            # Defaults adapt to tiny tables: the deepest legal tile not
            # exceeding the table height (explicit tile_rows still
            # raises past the table — the autotuner relies on that).
            rows = n if d % _LANE == 0 else n // 2
            tile_rows = max(_MIN_TILE,
                            min(dt, (rows // _MIN_TILE) * _MIN_TILE))
        if ring_depth is None:
            ring_depth = dr
    bp = -(-b // _CHUNK) * _CHUNK
    idx_p = jnp.concatenate(
        [idx.astype(jnp.int32), jnp.zeros((bp - b,), jnp.int32)])

    if d % _LANE == 0:
        if n < tile_rows:
            raise ValueError(f"table rows {n} must be >= {tile_rows}")
        out = _gather_sorted_pallas(table, idx_p, interpret, tile_rows,
                                    ring_depth)
        return out[:b]
    if d == 64:
        # Paired-row view: [N/2, 128].  The kernel moves full 128-lane
        # rows (a 64-lane DMA pads to 128 lanes in VMEM anyway); the
        # epilogue selects the requested half per original position.
        if n % 2 != 0:
            raise ValueError(f"d=64 path needs an even row count, got {n}")
        if n // 2 < tile_rows:
            raise ValueError(
                f"paired table rows {n // 2} must be >= {tile_rows}")
        idx_c = jnp.clip(idx_p, 0, n - 1)
        paired = _gather_sorted_pallas(table.reshape(n // 2, _LANE),
                                       idx_c // 2, interpret, tile_rows,
                                       ring_depth)
        half = jnp.take_along_axis(
            paired.reshape(bp, 2, 64),
            (idx_c % 2)[:, None, None], axis=1)[:, 0]
        return half[:b]
    raise ValueError(f"dim {d} must be a multiple of 128 (or exactly 64)")


def _xla_gather(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    return jnp.take(table, jnp.clip(idx, 0, table.shape[0] - 1), axis=0)


def pallas_gather_supported(table, idx, tile_rows: int = _MIN_TILE) -> bool:
    """Shape constraints of the tiled kernel (dtype-agnostic)."""
    n, d = table.shape
    if d % _LANE == 0:
        return n >= tile_rows
    return d == 64 and n % 2 == 0 and n // 2 >= tile_rows


def _auto_key(table, idx):
    return (int(table.shape[1]), int(idx.shape[0]), str(table.dtype))


def _fmt_params(params) -> str:
    return "xla" if params is None else f"t{params[0]}_r{params[1]}"


def autotune_gather_rows(table: jnp.ndarray, idx: jnp.ndarray,
                         iters: int = 3) -> str:
    """Sweep XLA vs the tiled kernel's (tile_rows, ring_depth) grid for
    this (row width, batch, dtype) and memoize the winner for
    ``gather_rows(force='auto')``.

    Call EAGERLY at warmup (loader construction / bench setup) — never
    from inside a trace.  Timing is fetch-synced (a host scalar fetch is
    the only sync that provably waits under the axon tunnel; see
    bench.py).  Off-TPU backends and unsupported shapes pin 'xla'.

    Returns ``'pallas'`` or ``'xla'`` (the per-candidate landscape is
    kept in :func:`autotune_table`).  The key includes the exact batch
    size, so an occupancy-capped shape is swept on its own rather than
    inheriting the full-cap winner.
    """
    key = _auto_key(table, idx)
    if key in _AUTO:
        return "xla" if _AUTO[key] is None else "pallas"
    winner = None          # None = xla; else (tile_rows, ring_depth)
    times: dict = {}
    if (jax.default_backend() == "tpu"
            and pallas_gather_supported(table, idx)):
        def timed(fn):
            float(fn(table, idx)[0, 0])  # compile + warm
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(table, idx)
            float(out[0, 0])             # fetch = true sync
            return (time.perf_counter() - t0) / iters * 1e3

        try:
            best = times["xla"] = timed(_xla_gather)
            for params in candidate_gather_params(table.shape[1],
                                                  table.dtype):
                if not pallas_gather_supported(table, idx, params[0]):
                    continue
                try:
                    t = timed(functools.partial(
                        gather_rows_pallas, tile_rows=params[0],
                        ring_depth=params[1]))
                except Exception:  # pragma: no cover - params bad on chip
                    continue
                times[_fmt_params(params)] = t
                if t < best:
                    best, winner = t, params
        except Exception:  # pragma: no cover - kernel unsupported on chip
            winner = None
    _AUTO[key] = winner
    _AUTO_TIMES[key] = times
    choice = "xla" if winner is None else "pallas"
    # Autotune runs host-side at warmup (never under trace — GLT010), so
    # the kernel decision is safe to publish here.
    from ..obs import metrics as _metrics

    _metrics.counter("glt.gather.autotune_runs",
                     "gather kernel sweep warmups").inc()
    _metrics.gauge("glt.gather.pallas_selected",
                   "1 if the last gather autotune picked the tiled "
                   "Pallas kernel", labels={"d": str(key[0])},
                   ).set(1.0 if choice == "pallas" else 0.0)
    return choice


def autotune_table() -> dict:
    """The sweep landscape, JSON-ready: ``{"d128_b139264_float32":
    {"winner": "t32_r8", "ms": {"xla": 4.1, "t8_r4": ...}}, ...}``.
    Empty entries mean the shape was pinned to XLA without a sweep
    (off-TPU or unsupported)."""
    out = {}
    for key, winner in _AUTO.items():
        d, b, dt = key
        out[f"d{d}_b{b}_{dt}"] = {
            "winner": _fmt_params(winner),
            "ms": {k: round(v, 4)
                   for k, v in _AUTO_TIMES.get(key, {}).items()},
        }
    return out


def reset_autotune() -> None:
    """Drop all memoized decisions (tests / re-calibration)."""
    _AUTO.clear()
    _AUTO_TIMES.clear()


def gather_rows(table: jnp.ndarray, idx: jnp.ndarray,
                force: str = "auto", dequant=None) -> jnp.ndarray:
    """Gather rows, choosing the best implementation.

    force: 'auto' | 'pallas' | 'xla'.  'auto' reads the decision table
    filled by :func:`autotune_gather_rows` (XLA until a measurement
    exists) and runs the winning (tile_rows, ring_depth) point.  The
    ``GLT_GATHER_FORCE`` env var overrides ``force``.

    dequant: optional :class:`~glt_tpu.store.quant.QuantSpec` for a
    compressed ``table``.  The Pallas arm widens rows to f32 in the
    copy-out epilogue (compressed bytes over the DMA ring); the XLA arm
    gathers compressed rows and dequantizes post-gather with the
    identical formula, so both arms agree bit-for-bit.  ``dequant=None``
    (or a raw spec) is byte-for-byte the pre-codec path.
    """
    env = os.environ.get("GLT_GATHER_FORCE")
    if env in ("pallas", "xla"):
        force = env
    if dequant is not None and dequant.is_compressed:
        if force == "pallas" or (force == "auto"
                                 and _AUTO.get(_auto_key(table, idx))
                                 is not None):
            params = _AUTO.get(_auto_key(table, idx))
            if params is not None:
                return gather_rows_pallas_dq(table, idx, dequant,
                                             tile_rows=params[0],
                                             ring_depth=params[1])
            return gather_rows_pallas_dq(table, idx, dequant)
        return quant.dequantize(_xla_gather(table, idx), dequant)
    if force == "pallas":
        params = _AUTO.get(_auto_key(table, idx))
        if params is not None:
            return gather_rows_pallas(table, idx, tile_rows=params[0],
                                      ring_depth=params[1])
        return gather_rows_pallas(table, idx)
    if force == "auto":
        params = _AUTO.get(_auto_key(table, idx))
        if params is not None:
            return gather_rows_pallas(table, idx, tile_rows=params[0],
                                      ring_depth=params[1])
    return _xla_gather(table, idx)
