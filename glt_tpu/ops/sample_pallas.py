"""Pallas degree-binned neighbor-sampling kernel: the sampling hot op.

TPU counterpart of the reference's warp-per-seed CUDA sampler
(``csrc/cuda/random_sampler.cu:87-106``): there, one warp walks each
seed row's adjacency with a Philox stream per thread.  Here the hop is
split along the compute/memory boundary:

* the **draw** (Floyd / with-replacement positions) stays in XLA via the
  shared :func:`~glt_tpu.ops.neighbor_sample._draw_positions` — pltpu's
  kernel PRNG is not threefry-bit-compatible with jax.random, and the
  draw is vector math, not the wall;
* the **neighbor read** — ``indices[start + pos]``, a random gather over
  the edge array, the bytes the sample stage exists to move — runs as
  tiled DMAs with the ring discipline of gather_pallas.py.

**Degree binning.**  Random row windows have wildly different widths on
power-law graphs; a tile mixing degree-4 and degree-4000 rows stalls on
its hub row.  Seeds are bucketed by degree class (``deg <= edges[b]``)
and stable-sorted by bin, so each per-bin kernel launch sees tiles of
comparable work and uses a window width ``W_b`` sized to its class.
Per row the kernel DMAs the 128-aligned window ``indices[estart :
estart + W_b]`` covering ``[start, start + deg)`` into a VMEM ring
(``ring_depth`` slots in flight while earlier rows copy out) and
selects the ``fanout`` drawn lanes with a broadcasted-iota masked sum
(dynamic LANE indexing is unsupported on TPU).  Rows above the last bin
edge (hubs) fall through to an XLA epilogue gather — a handful of rows
whose windows would blow the VMEM ring.

``autotune_sample`` sweeps (tile_rows, ring_depth, bin_edges) against
the XLA path per **exact** (batch, fanout, dtype) key — the exact-shape
keying gather learned the hard way (the BENCH_r05 capped-shape
inversion) is in from day one.  Off-TPU backends pin 'xla': on CPU the
seam resolves honestly to the XLA path (interpret mode exists for
correctness tests, not for winning benchmarks).
"""
from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..typing import PADDING_ID
from . import tpu_limits
from .neighbor_sample import (NeighborOutput, _row_offsets_and_degrees,
                              draw_positions)

_LANE = tpu_limits.LANE

# Decision table for sample_neighbors(force='auto'):
#   (batch, fanout, dtype) -> None (= xla) | (tile_rows, ring_depth,
#   bin_edges).  Filled by autotune_sample at eager warmup only.
_AUTO: dict = {}
# Per-key sweep timings for the bench's sample_autotune table:
#   (batch, fanout, dtype) -> {"xla": ms, "t128_r4_e64x512": ms, ...}.
_AUTO_TIMES: dict = {}

DEFAULT_BIN_EDGES = (64, 512)

# The (tile_rows, ring_depth, bin_edges) grid :func:`autotune_sample`
# sweeps — and the grid the static VMEM model (analysis/kernelmodel.py
# GLT017) verifies every point of, via VMEM_MODEL_DOMAIN below.
CANDIDATE_TILE_ROWS = (128, 256)
CANDIDATE_RING_DEPTHS = (4, 8)
CANDIDATE_BIN_EDGES = ((64, 512), (32, 256, 2048))

# Widest fanout the static VMEM model assumes (production fanouts run
# 5-25; the out block is [tile, fanout] so fanout bounds its lanes).
MODEL_MAX_FANOUT = 64

# Dimension domain for the static VMEM model: analysis/kernelmodel.py
# resolves this dict through the symbol table and checks the closed-form
# VMEM accounting of _binned_take_sorted at EVERY assignment of these
# symbols against tpu_limits.VMEM_BYTES.  The per-bin window width `w`
# is derived inside the function (`_bin_width(edge)` over the bin-edges
# layout), so the domain only needs the sweep axes themselves.
VMEM_MODEL_DOMAIN = {
    "tile": CANDIDATE_TILE_ROWS,
    "ring": CANDIDATE_RING_DEPTHS,
    "bin_edges": CANDIDATE_BIN_EDGES,
    "fanout": MODEL_MAX_FANOUT,
}


def _bin_width(edge: int) -> int:
    """Window lanes for a degree class: the smallest 128-multiple that
    covers any ``[start, start + deg)`` run with ``deg <= edge`` from a
    128-aligned (possibly end-clamped) window start — the aligned start
    can sit up to 127 elements before ``start``, hence the ``+127``.
    ``edge`` is always a static Python int (a bin-edges entry)."""
    return -(-(edge + _LANE - 1) // _LANE) * _LANE


def default_sample_params() -> tuple:
    """(tile_rows, ring_depth, bin_edges) fallback when no sweep ran."""
    return 128, 4, DEFAULT_BIN_EDGES


def candidate_sample_params() -> list:
    """The (tile_rows, ring_depth, bin_edges) grid
    :func:`autotune_sample` sweeps for one shape.  Two bin layouts — a
    shallow pair for near-uniform graphs and a three-class ladder whose
    top bin keeps power-law hubs off the XLA epilogue — crossed with the
    tile/ring depths that bound per-launch VMEM at ring * W * 4B."""
    return [(t, r, e)
            for e in CANDIDATE_BIN_EDGES
            for t in CANDIDATE_TILE_ROWS
            for r in CANDIDATE_RING_DEPTHS]


def pallas_sample_supported(indices: jnp.ndarray,
                            bin_edges=DEFAULT_BIN_EDGES) -> bool:
    """Autotune gate: sweeping a bin layout whose widest window exceeds
    the whole edge array is pointless (the kernel pads and still runs —
    correctness is unconditional — but XLA wins such toy graphs)."""
    return int(indices.shape[0]) >= _bin_width(max(bin_edges))


def _plan_binned(start, deg, bin_edges, tile: int, e: int):
    """XLA prologue: degree-class ids, clamped window starts, and the
    bin-sorted descriptor arrays the per-bin kernels consume.

    Every bin launch receives the FULL sorted descriptor set and skips
    foreign rows via a per-row ``binid == b`` guard — the guard is the
    same predicate on DMA start and wait, so the ring stays consistent
    across skipped rows.
    """
    b = deg.shape[0]
    nbins = len(bin_edges)
    edges_arr = jnp.asarray(bin_edges, jnp.int32)
    # deg <= edges[i] -> bin i; deg > edges[-1] -> nbins (hub epilogue).
    binid = jnp.searchsorted(edges_arr, deg, side="left").astype(jnp.int32)
    warr = jnp.asarray([_bin_width(x) for x in bin_edges] + [_LANE],
                       jnp.int32)
    w_row = warr[jnp.clip(binid, 0, nbins)]
    start = start.astype(jnp.int32)
    # 128-aligned window start, end-clamped so estart + W never overruns
    # the edge array; off + pos < W still holds because start + pos is a
    # valid edge index (< e <= estart + W).
    estart = jnp.clip((start // _LANE) * _LANE, 0,
                      jnp.maximum(e - w_row, 0))
    off = (start - estart).astype(jnp.int32)

    order = jnp.argsort(binid, stable=True)
    bp = -(-b // tile) * tile
    pad = bp - b
    binid_s = jnp.concatenate(
        [binid[order], jnp.full((pad,), nbins, jnp.int32)])
    estart_s = jnp.concatenate([estart[order], jnp.zeros((pad,), jnp.int32)])
    off_s = jnp.concatenate([off[order], jnp.zeros((pad,), jnp.int32)])
    # Original row i lives at sorted slot inv[i].
    inv = (jnp.zeros((b,), jnp.int32)
           .at[order].set(jnp.arange(b, dtype=jnp.int32)))
    return binid, binid_s, estart_s, off_s, order, inv, bp


def _make_bin_kernel(bin_id: int, tile: int, nbuf: int, w: int,
                     fanout: int):
    """Kernel for one degree class: per-row windowed DMA ring + masked
    lane select (dynamic sublane indexing is fine; dynamic LANE indexing
    is not — the iota/masked-sum picks the drawn lanes vectorized over
    fanout)."""

    def kernel(binid_ref, estart_ref, off_ref, pos_ref, src_ref, out_ref,
               chunks, sems):
        c = pl.program_id(0)
        base = c * tile

        def dma(j):
            slot = lax.rem(j, nbuf)
            return pltpu.make_async_copy(
                src_ref.at[pl.ds(estart_ref[base + j], w)],
                chunks.at[slot], sems.at[slot])

        # Fill the ring: up to `nbuf` row windows streaming before the
        # first copy-out.  Start and wait share the row's bin predicate,
        # so a skipped row never leaves a dangling DMA on its slot.
        for k in range(nbuf):
            @pl.when(binid_ref[base + k] == bin_id)
            def _():
                dma(k).start()

        def body(j, carry):
            slot = lax.rem(j, nbuf)

            @pl.when(binid_ref[base + j] == bin_id)
            def _():
                dma(j).wait()
                prow = pos_ref[j, :] + off_ref[base + j]      # [fanout]
                chunk = chunks[slot, :]                       # [w]
                sel = (lax.broadcasted_iota(jnp.int32, (fanout, w), 1)
                       == prow[:, None])
                vals = jnp.sum(jnp.where(sel, chunk[None, :], 0), axis=1)
                pl.store(out_ref, (pl.ds(j, 1), slice(None)),
                         vals[None, :].astype(jnp.int32))

            # Slot j % nbuf is free for row j + nbuf only after row j's
            # copy-out (or if row j never used it — then its last DMA
            # was already waited at an earlier body step).
            @pl.when((j + nbuf < tile)
                     & (binid_ref[base + j + nbuf] == bin_id))
            def _():
                dma(j + nbuf).start()

            return carry

        lax.fori_loop(0, tile, body, None)

    return kernel


def _binned_take_sorted(src, binid_s, estart_s, off_s, pos_s, bin_edges,
                        tile: int, ring: int, fanout: int,
                        interpret: bool):
    """Run one kernel per degree class over the full sorted descriptor
    set and merge per-bin outputs by the bin predicate."""
    bp = binid_s.shape[0]
    acc = jnp.zeros((bp, fanout), jnp.int32)
    for b_id, edge in enumerate(bin_edges):
        w = _bin_width(edge)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bp // tile,),
            in_specs=[
                # fanout (<=64) is deliberately narrower than the
                # 128-lane register: Mosaic pads the row in-register and
                # the padding cost (~2x on the [tile, fanout] blocks,
                # still <1% of VMEM) beats doubling every descriptor and
                # output buffer to a 128 stride end to end.
                # gltlint: disable-next=unaligned-tile-shape
                pl.BlockSpec((tile, fanout), lambda c, *_: (c, 0)),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            # Same narrow-fanout trade as the in block above.
            # gltlint: disable-next=unaligned-tile-shape
            out_specs=pl.BlockSpec((tile, fanout), lambda c, *_: (c, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[
                # ring=4 sits under the 8-sublane int32 floor; the slots
                # are row-granular DMA landing pads (never a tiled
                # compute operand), so the floor costs padding only —
                # deepening the ring to 8 would double live DMAs for no
                # measured gain (ROADMAP item 1 sweep).
                # gltlint: disable-next=unaligned-tile-shape
                pltpu.VMEM((ring, w), jnp.int32),
                pltpu.SemaphoreType.DMA((ring,)),
            ],
        )
        out_b = pl.pallas_call(
            _make_bin_kernel(b_id, tile, ring, w, fanout),
            out_shape=jax.ShapeDtypeStruct((bp, fanout), jnp.int32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(binid_s, estart_s, off_s, pos_s, src)
        acc = jnp.where((binid_s == b_id)[:, None], out_b, acc)
    return acc


def sample_neighbors_pallas(
    indptr: jnp.ndarray,
    indices: jnp.ndarray,
    seeds: jnp.ndarray,
    fanout: int,
    key: jax.Array,
    edge_ids=None,
    with_replacement: bool = False,
    with_edge: bool = True,
    params=None,
    interpret: bool = False,
    key_by: str = "slot",
) -> NeighborOutput:
    """Degree-binned Pallas neighbor sampling — bit-identical to
    :func:`~glt_tpu.ops.neighbor_sample.sample_neighbors` (same draw,
    same ``[B, fanout]`` -1-padded contract).

    Args:
      params: ``(tile_rows, ring_depth, bin_edges)`` from the autotune
        table, or None for :func:`default_sample_params`.
      interpret: run the kernels in Pallas interpret mode (CPU tests).
    """
    if fanout <= 0:
        raise ValueError(f"fanout must be positive, got {fanout}")
    tile, ring, bin_edges = (params if params is not None
                             else default_sample_params())
    seeds = seeds.astype(jnp.int32)
    b = seeds.shape[0]
    nbins = len(bin_edges)
    # Windowed DMAs read whole W-lane windows; graphs with fewer edges
    # than the widest window (tiny test fixtures, mostly) get the edge
    # arrays padded up so the end-clamped window start never underruns.
    # Padding lanes are never *selected* — start + pos is always a real
    # edge index for valid mask positions.
    wmax = _bin_width(max(bin_edges))
    e = max(int(indices.shape[0]), wmax)
    pad_e = e - int(indices.shape[0])
    start, deg = _row_offsets_and_degrees(indptr, seeds)
    pos, mask = draw_positions(deg, fanout, key, with_replacement, seeds,
                               key_by=key_by)
    pos0 = jnp.where(mask, pos, 0).astype(jnp.int32)

    binid, binid_s, estart_s, off_s, order, inv, bp = _plan_binned(
        start, deg, bin_edges, tile, e)
    pos_s = jnp.concatenate(
        [pos0[order], jnp.zeros((bp - b, fanout), jnp.int32)])
    flat = start[:, None] + pos0
    hub = binid >= nbins

    def take(src):
        src = src.astype(jnp.int32)
        if pad_e:
            src = jnp.concatenate([src, jnp.zeros((pad_e,), jnp.int32)])
        sorted_vals = _binned_take_sorted(
            src, binid_s, estart_s, off_s, pos_s, bin_edges, tile, ring,
            fanout, interpret)
        vals = jnp.take(sorted_vals, inv, axis=0)
        # Hub epilogue: rows past the last bin edge read straight from
        # HBM via XLA (index 0 for the non-hub majority — a cached row).
        safe = jnp.where(hub[:, None], flat, 0)
        return jnp.where(hub[:, None], src[safe], vals)

    nbrs = jnp.where(mask, take(indices), PADDING_ID).astype(jnp.int32)
    if not with_edge:
        eids = None
    elif edge_ids is None:
        eids = jnp.where(mask, flat, PADDING_ID).astype(jnp.int32)
    else:
        eids = jnp.where(mask, take(edge_ids), PADDING_ID).astype(jnp.int32)
    return NeighborOutput(nbrs=nbrs, eids=eids, mask=mask)


def _auto_key(batch: int, fanout: int, dtype) -> tuple:
    return (int(batch), int(fanout), str(jnp.dtype(dtype)))


def auto_params(batch: int, fanout: int, dtype):
    """The memoized winner for this exact shape, or None (= xla / not
    swept).  Read by ``sample_neighbors(force='auto')`` at trace time."""
    return _AUTO.get(_auto_key(batch, fanout, dtype))


def _fmt_params(params) -> str:
    if params is None:
        return "xla"
    t, r, e = params
    return f"t{t}_r{r}_e{'x'.join(str(x) for x in e)}"


def autotune_sample(indptr: jnp.ndarray, indices: jnp.ndarray,
                    seeds: jnp.ndarray, fanout: int,
                    key=None, edge_ids=None,
                    with_replacement: bool = False,
                    with_edge: bool = True, iters: int = 3) -> str:
    """Sweep XLA vs the binned kernel's (tile_rows, ring_depth,
    bin_edges) grid for this exact (batch, fanout, dtype) and memoize
    the winner for ``sample_neighbors(force='auto')``.

    Call EAGERLY at warmup (loader construction / bench setup) — never
    from inside a trace.  Timing is fetch-synced (the host scalar fetch
    is the only sync that provably waits under the axon tunnel; see
    bench.py).  Off-TPU backends and unsupported shapes pin 'xla' — on
    CPU the A/B seam resolves honestly to the XLA path.

    Returns ``'pallas'`` or ``'xla'``; the per-candidate landscape lands
    in :func:`sample_autotune_table`.  Keys by the exact batch size from
    day one — a capped loader shape gets its own sweep instead of
    inheriting the full-cap winner (the structural fix gather needed
    retrofitted in the BENCH_r05 round).
    """
    from ..obs import compilewatch as _compilewatch
    from ..obs import metrics as _metrics
    from .neighbor_sample import sample_neighbors as _sample_xla

    akey = _auto_key(seeds.shape[0], fanout, indices.dtype)
    if akey in _AUTO:
        return "xla" if _AUTO[akey] is None else "pallas"
    if key is None:
        key = jax.random.PRNGKey(0)
    winner = None          # None = xla; else (tile, ring, bin_edges)
    times: dict = {}
    if jax.default_backend() == "tpu":
        def timed(fn):
            int(fn(indptr, indices, seeds, key).nbrs[0, 0])  # compile+warm
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(indptr, indices, seeds, key)
            int(out.nbrs[0, 0])                  # fetch = true sync
            return (time.perf_counter() - t0) / iters * 1e3

        def xla_fn(ip, ix, sd, k):
            return _sample_xla(ip, ix, sd, fanout, k, edge_ids=edge_ids,
                               with_replacement=with_replacement,
                               with_edge=with_edge, force="xla")

        try:
            best = times["xla"] = timed(jax.jit(xla_fn))
            for params in candidate_sample_params():
                if not pallas_sample_supported(indices, params[2]):
                    continue

                def pfn(ip, ix, sd, k, _p=params):
                    return sample_neighbors_pallas(
                        ip, ix, sd, fanout, k, edge_ids=edge_ids,
                        with_replacement=with_replacement,
                        with_edge=with_edge, params=_p)

                try:
                    # Label the kernel-entry jit call site so
                    # glt.compile.*{program=} attributes the sweep's
                    # compiles and the storm detector covers them.
                    with _compilewatch.label(
                            f"sample_pallas_{_fmt_params(params)}"):
                        t = timed(jax.jit(pfn))
                except Exception:  # pragma: no cover - params bad on chip
                    continue
                times[_fmt_params(params)] = t
                if t < best:
                    best, winner = t, params
        except Exception:  # pragma: no cover - kernel unsupported on chip
            winner = None
    _AUTO[akey] = winner
    _AUTO_TIMES[akey] = times
    choice = "xla" if winner is None else "pallas"
    # Autotune runs host-side at warmup (never under trace — GLT010), so
    # the kernel decision is safe to publish here.
    _metrics.counter("glt.sample.autotune_runs",
                     "sample kernel sweep warmups").inc()
    _metrics.gauge("glt.sample.pallas_selected",
                   "1 if the last sample autotune picked the binned "
                   "Pallas kernel", labels={"fanout": str(fanout)},
                   ).set(1.0 if choice == "pallas" else 0.0)
    return choice


def sample_autotune_table() -> dict:
    """The sweep landscape, JSON-ready: ``{"b512_f10_int32": {"winner":
    "t128_r4_e64x512", "ms": {"xla": 2.1, ...}}, ...}``.  Empty ``ms``
    means the shape was pinned to XLA without a sweep (off-TPU)."""
    out = {}
    for akey, winner in _AUTO.items():
        b, f, dt = akey
        out[f"b{b}_f{f}_{dt}"] = {
            "winner": _fmt_params(winner),
            "ms": {k: round(v, 4)
                   for k, v in _AUTO_TIMES.get(akey, {}).items()},
        }
    return out


def reset_autotune() -> None:
    """Drop all memoized decisions (tests / re-calibration)."""
    _AUTO.clear()
    _AUTO_TIMES.clear()
