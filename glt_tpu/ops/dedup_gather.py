"""Dedup-aware feature row gather: fetch each unique row ONCE.

The sampler's node lists carry heavy duplication whenever the inducer is
bypassed — ``last_hop_dedup=False`` leaves every final-hop neighbor
un-deduped (power-law graphs repeat hub nodes across the whole frontier),
and raw multi-hop candidate lists repeat interior nodes across hops.  The
reference pays a hash-table pass to avoid refetching those rows
(csrc/cuda/inducer.cu); here the same economy is a pure-XLA sandwich that
stays inside the caller's jit:

    unique (first-occurrence order)  ->  row gather of the uniques
    ->  scatter rows back to every original batch position

The scatter-back step makes the output **bit-identical** to the naive
``table[ids]`` gather — same rows, same order, zeros at padding — so the
batch contract (``batch.node[:batch_size] == seeds``) is untouched: dedup
happens in row-fetch space, never in node-list space.

HBM economics: the unique gather touches ``U`` rows instead of ``B``
(``U/B`` = the dedup ratio the bench reports); the scatter-back reads the
``[B, d]`` unique-row block sequentially, which streams at full bandwidth
instead of random-row latency.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .gather_pallas import gather_rows
from .unique import unique_first_occurrence


def dedup_gather_rows(table: jnp.ndarray, ids: jnp.ndarray,
                      id2index: Optional[jnp.ndarray] = None,
                      force: str = "auto") -> jnp.ndarray:
    """Gather ``table`` rows for (duplicated, -1-padded) global ``ids``.

    Bit-identical to the naive masked gather
    ``where(ids >= 0, table[id2index[ids]], 0)`` but each distinct id's
    row is fetched from HBM exactly once.  jit/vmap/scan safe (static
    shapes throughout).

    Args:
      table: ``[N, d]`` feature rows (device-resident).
      ids: ``[B]`` int ids; negative entries are padding (zero rows out).
      id2index: optional ``[N]`` hotness indirection applied to unique
        ids before the row gather.
      force: gather implementation seam, see
        :func:`~glt_tpu.ops.gather_pallas.gather_rows`.
    """
    ids = ids.astype(jnp.int32)
    uniq, inv, _ = unique_first_occurrence(ids)
    uvalid = uniq >= 0
    uidx = jnp.where(uvalid, uniq, 0)
    if id2index is not None:
        uidx = jnp.take(id2index, uidx, axis=0, mode="clip")
    urows = jnp.where(uvalid[:, None], gather_rows(table, uidx, force), 0)
    # Scatter-back: position i reads unique slot inv[i] (-1 = padding).
    rows = jnp.take(urows, jnp.clip(inv, 0, inv.shape[0] - 1), axis=0)
    return jnp.where((inv >= 0)[:, None], rows, 0)


def dedup_counts(ids: jnp.ndarray) -> tuple:
    """``(valid, unique)`` id counts as device scalars (bench's dedup
    ratio = unique/valid; no host sync here)."""
    ids = ids.astype(jnp.int32)
    res = unique_first_occurrence(ids)
    return jnp.sum((ids >= 0).astype(jnp.int32)), res.count
