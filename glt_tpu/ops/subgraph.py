"""Induced-subgraph extraction over a node set, XLA-native.

Rebuild of ``csrc/cuda/subgraph_op.cu``: the CUDA op inserts the node set
into a hash table, scans every node's full CSR row keeping neighbors present
in the set (GetNbrsNumKernel, subgraph_op.cu:34-68), prefix-sums, and emits
relabeled rows/cols/eids.

TPU design: membership testing uses :func:`relabel_by_reference` (sorted
lookup instead of a hash probe), and the per-node row scan is bounded by a
static ``max_degree`` cap so the output shape ``[S, max_degree]`` is known at
trace time.  Callers size ``max_degree`` from host-side degree stats (the
loader rounds it up to a power of two to bound recompilation).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from ..typing import PADDING_ID
from .neighbor_sample import _row_offsets_and_degrees
from .unique import relabel_by_reference


class SubGraphOutput(NamedTuple):
    """Relabeled induced subgraph (cf. ``CUDASubGraphOp::NodeSubGraph``)."""
    rows: jnp.ndarray  # [S * max_degree] local src index, -1 padded
    cols: jnp.ndarray  # [S * max_degree] local dst index, -1 padded
    eids: jnp.ndarray  # [S * max_degree] global edge ids, -1 padded
    mask: jnp.ndarray  # [S * max_degree] bool


def node_subgraph(
    indptr: jnp.ndarray,
    indices: jnp.ndarray,
    nodes: jnp.ndarray,
    max_degree: int,
    edge_ids: Optional[jnp.ndarray] = None,
) -> SubGraphOutput:
    """Extract the subgraph induced by ``nodes`` (unique, -1 padded).

    Edges whose source sits beyond ``max_degree`` entries into its CSR row
    are dropped; callers must pick ``max_degree`` >= the max degree of the
    node set for exact extraction (subgraph_op.cu:133 scans full rows — our
    cap is the static-shape tradeoff, checked by the loader).
    """
    s = nodes.shape[0]
    start, deg = _row_offsets_and_degrees(indptr, nodes.astype(jnp.int32))
    start = start.astype(jnp.int32)

    offs = jnp.arange(max_degree, dtype=jnp.int32)[None, :]          # [1, D]
    in_row = offs < deg[:, None]                                     # [S, D]
    flat = start[:, None] + jnp.where(in_row, offs, 0)
    dst_global = jnp.where(in_row, indices[flat], PADDING_ID).astype(jnp.int32)

    local_dst = relabel_by_reference(nodes, dst_global.ravel()).reshape(s, max_degree)
    keep = in_row & (local_dst >= 0)

    local_src = jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.int32)[:, None], (s, max_degree)
    )
    rows = jnp.where(keep, local_src, PADDING_ID).ravel()
    cols = jnp.where(keep, local_dst, PADDING_ID).ravel()
    if edge_ids is None:
        eids = jnp.where(keep, flat, PADDING_ID).ravel()
    else:
        eids = jnp.where(keep, edge_ids[flat], PADDING_ID).ravel()
    return SubGraphOutput(rows=rows, cols=cols, eids=eids.astype(jnp.int32), mask=keep.ravel())
