"""TPU device limits: the single source of truth for kernel sizing.

Every number here is a hardware (or hardware-adjacent) constant that
both the Pallas kernels (``ops/``) and the static device-program
verifier (``analysis/kernelmodel.py``) reason about.  Keeping them in
one importable module means the kernels and the analyzer can never
disagree: the analyzer resolves these names through its symbol table,
so editing a value here re-checks every kernel against the new limit
on the next lint run.

Sources: pallas_guide.md "Tiling Constraints" / "Memory Spaces"
(VMEM ~16 MB/core; min tile (sublane, lane) per dtype: float32 (8,128),
bfloat16 (16,128), int8/fp8 (32,128)) and the DMA-depth calibration of
gather_pallas.py round 5 (~16 KB block DMAs are where a v5-class DMA
engine streams instead of paying setup per transfer).

Stdlib-only on purpose: the analyzer's CI job runs without the JAX
stack, and nothing below needs an array library.
"""
from __future__ import annotations

# Per-core VMEM.  The hard ceiling the closed-form VMEM model
# (GLT017 vmem-budget-exceeded) checks every candidate kernel
# parameter point against.
VMEM_BYTES = 16 * 2**20

# Last-dimension register width: every VMEM tile is LANE lanes wide,
# and narrower last dims are padded up to it.
LANE = 128

# Minimum second-to-last (sublane) tile dim by dtype width: 4-byte
# types tile (8, 128), 2-byte (16, 128), 1-byte (32, 128).
SUBLANE_F32 = 8
SUBLANE_BF16 = 16
SUBLANE_INT8 = 32

# Block-DMA byte depth the width-specialized gather defaults aim for:
# deep enough to stream, small enough to keep ring slots cheap.
DMA_DEPTH_TARGET_BYTES = 1 << 14

# Widest feature row (in lanes) the static VMEM model assumes for
# runtime-sized last dims (a table's `d` is only known at trace time;
# the model bounds it here so the closed-form accounting stays total).
MODEL_MAX_LANES = 2048


def sublane_min(itemsize: int) -> int:
    """Smallest legal sublane tile dim for an ``itemsize``-byte dtype
    (f32 8, bf16 16, int8/fp8 32 — pallas_guide.md)."""
    return max(SUBLANE_F32, 32 // max(int(itemsize), 1))
