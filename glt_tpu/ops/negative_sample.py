"""Random negative edge sampling, XLA-native.

Rebuild of ``csrc/cuda/random_negative_sampler.cu``: the CUDA kernel draws
uniform (row, col) pairs, rejects existing edges with a per-row binary search
(``EdgeInCSR``, random_negative_sampler.cu:37-54) over ``trials_num``
retries, compacts survivors with thrust, and optionally pads with non-strict
draws (:153-160).

TPU design: draw all ``trials x num`` candidates at once, test them with a
vectorised 32-step binary search over column-sorted CSR rows, and pick the
first passing trial per slot with an argmin — no compaction pass, no dynamic
shapes, no host sync.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..typing import PADDING_ID

_INT32_MAX = jnp.iinfo(jnp.int32).max


def edge_in_csr(
    indptr: jnp.ndarray,
    sorted_indices: jnp.ndarray,
    src: jnp.ndarray,
    dst: jnp.ndarray,
) -> jnp.ndarray:
    """Vectorised membership test: does edge (src, dst) exist?

    ``sorted_indices`` must have columns sorted within each CSR row (the
    ``Graph`` class maintains this auxiliary view).  Classic branchless
    binary search, unrolled to 32 steps — same primitive the CUDA kernel
    runs per thread (random_negative_sampler.cu:37-54).
    """
    valid = (src >= 0) & (dst >= 0)
    s = jnp.where(valid, src, 0)
    lo = indptr[s].astype(jnp.int32)
    hi = indptr[s + 1].astype(jnp.int32)
    row_end = hi
    d = dst.astype(jnp.int32)
    last = sorted_indices.shape[0] - 1
    # Branchless lower_bound over [lo, hi): 32 unrolled halving steps cover
    # any int32-sized row.
    for _ in range(32):
        cond = lo < hi
        mid = lo + (hi - lo) // 2  # overflow-safe for E > 2^30
        mid_val = sorted_indices[jnp.clip(mid, 0, last)]
        go_right = cond & (mid_val < d)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(cond & ~go_right, mid, hi)
    in_row = lo < row_end
    exists = in_row & (sorted_indices[jnp.clip(lo, 0, last)] == d)
    return exists & valid


def weighted_draw(key: jax.Array, cdf: jnp.ndarray, shape) -> jnp.ndarray:
    """Categorical node draw (with replacement) by inverse-CDF lookup.

    ``cdf`` is the normalized cumulative node-weight vector (last entry
    1.0).  Replaces the reference/PyG ``torch.multinomial(weight, ...,
    replacement=True)`` draw (sampler/base.py:84-145 ``weight``) with a
    branchless ``searchsorted`` — one fused gather-free kernel, no host
    sync, exact per-draw distribution.
    """
    u = jax.random.uniform(key, shape)
    idx = jnp.searchsorted(cdf, u, side="right")
    return jnp.clip(idx, 0, cdf.shape[0] - 1).astype(jnp.int32)


def weight_to_cdf(weight) -> jnp.ndarray:
    """Normalized inclusive cumsum of a non-negative node-weight vector."""
    w = jnp.asarray(weight, jnp.float32)
    c = jnp.cumsum(w)
    return c / c[-1]


class NegativeSampleOutput(NamedTuple):
    src: jnp.ndarray   # [num] sampled source ids (-1 where nothing found)
    dst: jnp.ndarray   # [num]
    mask: jnp.ndarray  # [num] bool


def sample_negative_edges(
    indptr: jnp.ndarray,
    sorted_indices: jnp.ndarray,
    num: int,
    key: jax.Array,
    num_nodes: int,
    trials: int = 5,
    padding: bool = True,
    num_dst_nodes: int = None,
    src_cdf: jnp.ndarray = None,
    dst_cdf: jnp.ndarray = None,
) -> NegativeSampleOutput:
    """Draw ``num`` node pairs that are (probably) not edges.

    Mirrors ``CUDARandomNegativeSampler::Sample``
    (random_negative_sampler.cu:118): ``trials`` strict rejection rounds,
    then, when ``padding`` is set, unfilled slots fall back to their last
    (possibly positive) draw so the output is always exactly ``num`` pairs —
    the reference's non-strict padding pass (:153-160).

    Hetero seed-edge types pass ``num_dst_nodes`` (dst drawn over the
    destination type's id space); ``src_cdf``/``dst_cdf`` switch the
    uniform draws to weighted ones (``NegativeSampling.weight``).
    """
    if num_dst_nodes is None:
        num_dst_nodes = num_nodes
    ks, kd = jax.random.split(key)
    if src_cdf is not None:
        src = weighted_draw(ks, src_cdf, (trials, num))
    else:
        src = jax.random.randint(ks, (trials, num), 0, num_nodes,
                                 dtype=jnp.int32)
    if dst_cdf is not None:
        dst = weighted_draw(kd, dst_cdf, (trials, num))
    else:
        dst = jax.random.randint(kd, (trials, num), 0, num_dst_nodes,
                                 dtype=jnp.int32)
    exists = edge_in_csr(indptr, sorted_indices, src.ravel(), dst.ravel())
    exists = exists.reshape(trials, num)
    # First passing trial per slot; INT32_MAX when none pass.
    trial_idx = jnp.arange(trials, dtype=jnp.int32)[:, None]
    score = jnp.where(exists, _INT32_MAX, trial_idx)
    best = jnp.argmin(score, axis=0)
    ok = jnp.take_along_axis(~exists, best[None, :], axis=0)[0]
    pick = lambda a: jnp.take_along_axis(a, best[None, :], axis=0)[0]
    out_src, out_dst = pick(src), pick(dst)
    if padding:
        return NegativeSampleOutput(out_src, out_dst, jnp.ones_like(ok))
    out_src = jnp.where(ok, out_src, PADDING_ID)
    out_dst = jnp.where(ok, out_dst, PADDING_ID)
    return NegativeSampleOutput(out_src, out_dst, ok)
