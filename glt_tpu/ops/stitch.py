"""Stitch per-partition partial sampling results back into seed order.

Rebuild of ``csrc/cuda/stitch_sample_results.cu``: the CUDA kernel scatters
each partition's neighbor runs into a global ragged output using index lists
and a cumsum of neighbor counts (:27-56).  With static ``[B, fanout]`` blocks
stitching degenerates to a single scatter per partition — no offsets needed.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from ..typing import PADDING_ID


def stitch_sample_results(
    num_seeds: int,
    idx_list: Sequence[jnp.ndarray],
    nbrs_list: Sequence[jnp.ndarray],
    eids_list: Sequence[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter partition-local ``[b_p, fanout]`` blocks into seed order.

    Args:
      num_seeds: total number of seeds B.
      idx_list: per partition, ``[b_p]`` original seed positions (-1 padded).
      nbrs_list/eids_list: per partition, ``[b_p, fanout]`` sampled blocks.

    Returns:
      ``(nbrs, eids)`` of shape ``[B, fanout]``, -1 padded.
    """
    fanout = nbrs_list[0].shape[1]
    nbrs = jnp.full((num_seeds + 1, fanout), PADDING_ID, jnp.int32)
    eids = jnp.full((num_seeds + 1, fanout), PADDING_ID, jnp.int32)
    for idx, nb, ei in zip(idx_list, nbrs_list, eids_list):
        # -1 indices route to the spill row (num_seeds), sliced off below.
        at = jnp.where(idx >= 0, idx, num_seeds)
        nbrs = nbrs.at[at].set(nb.astype(jnp.int32))
        eids = eids.at[at].set(ei.astype(jnp.int32))
    return nbrs[:num_seeds], eids[:num_seeds]
