"""Static-shape, first-occurrence-order unique + relabel.

This is the TPU-native replacement for the reference's GPU hash-table
inducer (``csrc/cuda/hash_table.cu``, ``csrc/cuda/inducer.cu``): the CUDA
design deduplicates node ids with an ``atomicCAS`` open-addressing table and
emits unique keys in first-occurrence order.  Hash tables are a poor fit for
the TPU's vector units, so we obtain identical semantics with sorts and
segmented scans — O(M log M), fully static shapes, jit/vmap/shard_map safe.

Key invariant preserved from the reference: unique ids come out in **first
occurrence order**, so when seeds are placed at the front of the input, the
output node list starts with the seeds — loaders rely on
``batch.node[:batch_size] == seeds`` exactly as GLT does
(csrc/cuda/inducer.cu:75-95, python/loader/node_loader.py:85).

Negative ids are padding (PADDING_ID) and are ignored; they map to inverse
index -1 and never appear among the unique ids.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

_INT32_MAX = jnp.iinfo(jnp.int32).max


class UniqueResult(NamedTuple):
    uniques: jnp.ndarray  # [M] unique ids in first-occurrence order, -1 padded
    inverse: jnp.ndarray  # [M] position of each input id in `uniques` (-1 for padding)
    count: jnp.ndarray    # [] int32 number of valid uniques


def unique_first_occurrence(ids: jnp.ndarray) -> UniqueResult:
    """Deduplicate ``ids`` preserving first-occurrence order.

    Args:
      ids: ``[M]`` int array; negative entries are padding.

    Returns:
      ``UniqueResult(uniques, inverse, count)`` with static shapes ``[M]``.
    """
    ids = ids.astype(jnp.int32)
    m = ids.shape[0]
    valid = ids >= 0
    # Padding sorts to the back.
    keys = jnp.where(valid, ids, _INT32_MAX)

    # Stable sort so the head of each equal-id run carries the smallest
    # original position == the first occurrence.
    perm = jnp.argsort(keys, stable=True)
    sorted_keys = keys[perm]

    prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), sorted_keys[:-1]])
    heads = (sorted_keys != prev) & (sorted_keys != _INT32_MAX)
    # Run index of every sorted element (garbage for padding; masked later).
    run_of_sorted = jnp.cumsum(heads.astype(jnp.int32)) - 1
    count = jnp.sum(heads.astype(jnp.int32))

    # Per-run first-occurrence position and id, scattered at head slots.
    # Scatter target M+1 with an overflow slot for non-heads / padding.
    scatter_idx = jnp.where(heads, run_of_sorted, m)
    first_pos = (
        jnp.full((m + 1,), _INT32_MAX, jnp.int32)
        .at[scatter_idx]
        .min(perm.astype(jnp.int32))[:m]
    )
    run_ids = (
        jnp.full((m + 1,), -1, jnp.int32).at[scatter_idx].max(sorted_keys)[:m]
    )
    run_ids = jnp.where(run_ids == _INT32_MAX, -1, run_ids)

    # Order runs by first occurrence; padding runs (first_pos == INT32_MAX)
    # sort to the back.
    order = jnp.argsort(first_pos, stable=True)
    uniques = run_ids[order]

    # rank[r] = final position of run r.
    rank = jnp.zeros((m,), jnp.int32).at[order].set(jnp.arange(m, dtype=jnp.int32))
    inv_sorted = rank[jnp.clip(run_of_sorted, 0, m - 1)]
    inverse = jnp.zeros((m,), jnp.int32).at[perm].set(inv_sorted)
    inverse = jnp.where(valid, inverse, -1)
    return UniqueResult(uniques, inverse, count)


class DenseInduceState(NamedTuple):
    """Carry of the dense (scatter-based) incremental inducer.

    ``seen`` is a ``[num_nodes + 2]`` int32 map: 0 = unseen, else the
    committed encoding ``_LOCAL_BASE - local_id`` (decode with
    ``_LOCAL_BASE - seen[id]``; between the two scatters of a
    :func:`dense_induce` call it may transiently hold provisional
    markers — see the band comment there).  Slot ``N`` absorbs padding
    *reads*; slot ``N + 1`` absorbs dump *writes*.  ``node_buf`` is the
    cumulative ``[capacity + 1]`` unique-node list (-1 padded; last slot
    is the write dump), ``count`` the number of valid uniques.
    """
    seen: jnp.ndarray
    node_buf: jnp.ndarray
    count: jnp.ndarray


def dense_map_fits(num_nodes: int, budget_bytes: int = 1 << 30) -> bool:
    """Whether a dense id->local map for ``num_nodes`` fits the budget
    (the 'auto' dedup heuristic shared by every sampler)."""
    return num_nodes * 4 <= budget_bytes


def dense_induce_init(num_nodes: int, capacity: int) -> DenseInduceState:
    """Fresh per-batch state (the analog of ``Inducer::Reset``,
    csrc/cpu/inducer.cc; allocating zeros is a ~4B/node memset)."""
    return DenseInduceState(
        seen=jnp.zeros((num_nodes + 2,), jnp.int32),
        node_buf=jnp.full((capacity + 1,), -1, jnp.int32),
        count=jnp.zeros((), jnp.int32),
    )


# Encoded `seen` values (see dense_induce): 0 = unseen; provisional
# in-batch representative markers live in (0, _PROV_BASE]; committed
# local ids live in [_LOCAL_BASE - count, _LOCAL_BASE].  The committed
# band sits strictly above the provisional band, so one scatter-MAX both
# detects first occurrences and preserves existing assignments.  Hard
# bounds: per-call candidate width m < _PROV_BASE (validated below) and
# cumulative count < _LOCAL_BASE - _PROV_BASE (~1.04e9; unreachable —
# count is bounded by node_buf's capacity, itself an int32 array size).
_PROV_BASE = 1 << 25
_LOCAL_BASE = 1 << 30


def dense_induce(state: DenseInduceState, cand: jnp.ndarray
                 ) -> tuple:
    """Insert ``cand`` (negative = padding) into the cumulative unique
    list; return ``(state, local)`` where ``local[i]`` is the compact
    index of ``cand[i]`` (-1 for padding).

    This is the hash-table inducer's contract
    (``CUDAInducer::InduceNext``, csrc/cuda/inducer.cu:95) implemented
    with dense scatters instead of sorts: on TPU, an O(N) id->local map
    beats the O(M log^2 M) bitonic argsorts of
    :func:`unique_first_occurrence` by ~4x at frontier widths >= 100k.
    Random element-ops (~7ns each on v5-lite regardless of table size)
    dominate, so the hop costs exactly FOUR per candidate — scatter-max
    of an encoded marker, read-back, commit scatter, resolve read — via
    a single map whose value encoding makes existing assignments beat
    in-batch provisional markers under max.  New nodes receive
    consecutive local ids in first-occurrence order, so per-hop frontier
    slices of ``node_buf`` are exactly the newly discovered nodes, and
    seeds placed first keep ``node_buf[:batch] == seeds``.
    """
    seen, node_buf, count = state
    n2 = seen.shape[0]
    n = n2 - 2
    m = cand.shape[0]
    if m >= _PROV_BASE:
        raise ValueError(f"candidate width {m} exceeds the {_PROV_BASE} "
                         f"encoding band")
    cand = cand.astype(jnp.int32)
    valid = cand >= 0
    safe = jnp.where(valid, cand, n)                     # padding reads slot n
    pos = jnp.arange(m, dtype=jnp.int32)

    # Op 1 (scatter-max): provisional marker _PROV_BASE - pos.  Unseen
    # slots (0) lose to any marker; among markers the smallest pos wins;
    # committed ids (>= _LOCAL_BASE - cap) beat every marker.
    seen = seen.at[jnp.where(valid, safe, n + 1)].max(
        jnp.where(valid, _PROV_BASE - pos, 0))
    # Op 2 (gather): who won each id?
    won = seen[safe]
    is_first = valid & (won == _PROV_BASE - pos)  # my marker won => new id
    local_new = count + jnp.cumsum(is_first.astype(jnp.int32)) - 1
    # Op 3 (scatter): commit final encodings for the new ids (ids are
    # unique among is_first slots; dump slot n+1 absorbs the rest).
    seen = seen.at[jnp.where(is_first, safe, n + 1)].set(
        jnp.where(is_first, _LOCAL_BASE - local_new, 0))
    # Op 4 (gather): resolve every candidate through the committed map.
    local = jnp.where(valid, _LOCAL_BASE - seen[safe], -1)
    dump = node_buf.shape[0] - 1
    # Defensive clamp: callers that size node_buf below the worst case
    # (capped hetero buffers) overflow into the dump slot; the node keeps
    # its >=capacity local id in `seen`, so its edges are maskable.
    slot = jnp.minimum(jnp.where(is_first, local_new, dump), dump)
    node_buf = node_buf.at[slot].set(jnp.where(is_first, cand, -1))
    count = count + jnp.sum(is_first.astype(jnp.int32))
    return DenseInduceState(seen, node_buf, count), local


def dense_induce_final(state: DenseInduceState, cand: jnp.ndarray
                       ) -> tuple:
    """Last-hop :func:`dense_induce`: same contract, one fewer map op.

    After the final hop no later hop reads the ``seen`` map, so the
    commit scatter (op 3 of :func:`dense_induce`) is dead work; losers of
    the provisional scatter-max resolve through an ``[m]``-sized gather
    of the winner's freshly assigned id instead of re-reading the map.
    Saves one full-width random scatter at the widest frontier (the
    single most expensive op of the whole pipeline).  The returned
    ``state.seen`` is stale (still holds provisional markers) and MUST
    NOT be fed to another induce call; ``node_buf``/``count`` are exact.
    """
    seen, node_buf, count = state
    n2 = seen.shape[0]
    n = n2 - 2
    m = cand.shape[0]
    if m >= _PROV_BASE:
        raise ValueError(f"candidate width {m} exceeds the {_PROV_BASE} "
                         f"encoding band")
    cand = cand.astype(jnp.int32)
    valid = cand >= 0
    safe = jnp.where(valid, cand, n)
    pos = jnp.arange(m, dtype=jnp.int32)

    # Op 1 (scatter-max) + op 2 (gather): identical to dense_induce.
    seen = seen.at[jnp.where(valid, safe, n + 1)].max(
        jnp.where(valid, _PROV_BASE - pos, 0))
    won = seen[safe]
    is_first = valid & (won == _PROV_BASE - pos)
    local_new = count + jnp.cumsum(is_first.astype(jnp.int32)) - 1
    # Resolve WITHOUT the commit scatter: committed winners (previous
    # hops) decode in-register; marker winners (this call) are by
    # construction is_first slots, so an [m]-gather of local_new at the
    # winner position replaces the map read-back.
    winner_pos = jnp.clip(_PROV_BASE - won, 0, m - 1)
    local = jnp.where(won > _PROV_BASE, _LOCAL_BASE - won,
                      local_new[winner_pos])
    local = jnp.where(valid, local, -1)
    dump = node_buf.shape[0] - 1
    slot = jnp.minimum(jnp.where(is_first, local_new, dump), dump)
    node_buf = node_buf.at[slot].set(jnp.where(is_first, cand, -1))
    count = count + jnp.sum(is_first.astype(jnp.int32))
    return DenseInduceState(seen, node_buf, count), local


def relabel_by_reference(reference_ids: jnp.ndarray, query_ids: jnp.ndarray) -> jnp.ndarray:
    """Map each ``query_id`` to its position in ``reference_ids``.

    ``reference_ids`` must be a -1-padded first-occurrence-unique list (as
    produced by :func:`unique_first_occurrence`); every valid query id must
    appear in it.  Returns -1 for padding queries.  This replaces the
    reference's persistent per-batch hash-table lookups
    (include/hash_table.cuh:43-55) with a sort-free searchsorted pass.
    """
    m = reference_ids.shape[0]
    ref_keys = jnp.where(reference_ids >= 0, reference_ids, _INT32_MAX)
    order = jnp.argsort(ref_keys)
    sorted_ref = ref_keys[order]
    q = jnp.where(query_ids >= 0, query_ids, _INT32_MAX - 1)
    pos = jnp.searchsorted(sorted_ref, q)
    pos = jnp.clip(pos, 0, m - 1)
    hit = sorted_ref[pos] == q
    local = jnp.where(hit, order[pos], -1)
    return jnp.where(query_ids >= 0, local, -1).astype(jnp.int32)
