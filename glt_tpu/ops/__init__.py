from .dedup_gather import dedup_counts, dedup_gather_rows
from .fused_frontier import (
    FusedFrontier,
    fused_frontier,
    fused_frontier_supported,
)
from .gather_pallas import (
    autotune_gather_rows,
    autotune_table,
    gather_rows,
    gather_rows_pallas,
)
from .neighbor_sample import NeighborOutput, lookup_degrees, sample_neighbors
from .negative_sample import NegativeSampleOutput, edge_in_csr, sample_negative_edges
from .sample_pallas import (
    autotune_sample,
    sample_autotune_table,
    sample_neighbors_pallas,
)
from .stitch import stitch_sample_results
from .subgraph import SubGraphOutput, node_subgraph
from .unique import UniqueResult, relabel_by_reference, unique_first_occurrence

__all__ = [
    "NeighborOutput", "lookup_degrees", "sample_neighbors",
    "NegativeSampleOutput", "edge_in_csr", "sample_negative_edges",
    "stitch_sample_results",
    "SubGraphOutput", "node_subgraph",
    "UniqueResult", "relabel_by_reference", "unique_first_occurrence",
    "dedup_counts", "dedup_gather_rows",
    "autotune_gather_rows", "autotune_table", "gather_rows", "gather_rows_pallas",
    "autotune_sample", "sample_autotune_table", "sample_neighbors_pallas",
    "FusedFrontier", "fused_frontier", "fused_frontier_supported",
]
