"""Fixed-fanout random neighbor sampling over CSR, XLA-native.

TPU rethink of the reference's CUDA sampler (``csrc/cuda/random_sampler.cu``):
the CUDA kernel assigns one warp per seed row and runs reservoir sampling over
the row's full adjacency (random_sampler.cu:87-106), sizing its ragged output
with a cub scan + a forced device->host sync (random_sampler.cu:288-300).

On TPU we avoid both the O(degree) reservoir walk and the dynamic output:

* output is **static** ``[num_seeds, fanout]`` with sentinel padding
  (PADDING_ID = -1), so the whole multi-hop pipeline stays inside one jit;
* without-replacement sampling uses **Floyd's algorithm** — O(fanout^2)
  per row independent of degree, a much better fit for power-law graphs
  than a reservoir pass over million-edge rows;
* randomness is counter-based (threefry via jax.random), keyed per
  (key, slot), reproducible under jit/vmap/shard_map — mirroring the
  curand Philox stream-per-thread setup (random_sampler.cu:71-73).

All functions are pure and shard_map-compatible: inputs/outputs are plain
arrays, no host syncs.

**The A/B seam** (the gather_pallas.py pattern applied to sampling):
``sample_neighbors(force=...)`` routes the memory-bound half of the hop
— the ``indices[start + pos]`` / ``edge_ids[start + pos]`` random reads
— through either XLA's generic gather or the degree-binned Pallas DMA
kernel (:mod:`.sample_pallas`).  The *draw* (Floyd / with-replacement
positions) always runs here in XLA: pltpu's PRNG is not threefry-bit-
compatible with jax.random, and bit-identical output across paths is
what lets every existing sampler/loader/dist test double as a
correctness oracle.  ``force='auto'`` serves the winner memoized by
:func:`~glt_tpu.ops.sample_pallas.autotune_sample` per exact
(batch, fanout, dtype) key — XLA until a measurement exists.  The
``GLT_SAMPLE_FORCE`` env var overrides (``pallas``/``xla``/
``interpret`` — the last runs the Pallas path in interpret mode so the
seam is exercisable end to end on CPU).
"""
from __future__ import annotations

import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..typing import PADDING_ID


class NeighborOutput(NamedTuple):
    """One-hop sampling result (cf. sampler/base.py:301 ``NeighborOutput``)."""
    nbrs: jnp.ndarray       # [B, fanout] neighbor global ids, -1 padded
    eids: Optional[jnp.ndarray]  # [B, fanout] global edge ids, -1 padded
    mask: jnp.ndarray       # [B, fanout] bool validity


def _row_offsets_and_degrees(indptr, seeds):
    """Per-seed CSR offsets/degrees; invalid (negative) seeds get degree 0."""
    valid = seeds >= 0
    safe = jnp.where(valid, seeds, 0)
    start = indptr[safe]
    deg = indptr[safe + 1] - start
    deg = jnp.where(valid, deg, 0)
    return start, deg.astype(jnp.int32)


def _draw_positions(deg: jnp.ndarray, fanout: int, key: jax.Array,
                    with_replacement: bool):
    """Per-row draw positions + validity mask: ``(pos [B, fanout],
    mask [B, fanout])`` with ``pos[i, k] < max(deg[i], 1)``.

    Shared by the XLA and Pallas sampling paths — the draw is the
    bit-identity anchor between them (both gather ``indices[start +
    where(mask, pos, 0)]``), so it must run through jax.random on both.
    """
    b = deg.shape[0]
    slot_ids = jnp.arange(fanout, dtype=jnp.int32)  # [k]

    if with_replacement:
        pos = jax.random.randint(
            key, (b, fanout), 0, jnp.maximum(deg, 1)[:, None], dtype=jnp.int32
        )
        mask = (slot_ids[None, :] < jnp.where(deg > 0, fanout, 0)[:, None])
        return pos, mask

    # Floyd's uniform k-subset algorithm, unrolled over the (static,
    # small) fanout.  For rows with deg <= fanout we take slots 0..deg-1
    # directly; Floyd only engages when deg > fanout.
    chosen = jnp.full((b, fanout), -1, jnp.int32)
    keys = jax.random.split(key, fanout)
    for i in range(fanout):
        j = deg - fanout + i                       # [B], >= 0 when deg > fanout
        t = jax.random.randint(
            keys[i], (b,), 0, jnp.maximum(j + 1, 1), dtype=jnp.int32
        )
        dup = jnp.any(chosen == t[:, None], axis=1)
        floyd_pos = jnp.where(dup, j, t)
        pos_i = jnp.where(deg > fanout, floyd_pos, i)
        chosen = chosen.at[:, i].set(pos_i)
    mask = slot_ids[None, :] < jnp.minimum(deg, fanout)[:, None]
    return chosen, mask


def _draw_positions_by_id(deg: jnp.ndarray, fanout: int, key: jax.Array,
                          with_replacement: bool, seeds: jnp.ndarray):
    """Layout-invariant draw: positions keyed per ``(key, seed id)``.

    :func:`_draw_positions` keys randomness per (key, buffer slot), so
    the same id draws *different* neighbors when it appears at a
    different position (or more than once) in the request buffer.  The
    hierarchical dedup-then-exchange transport
    (:class:`glt_tpu.parallel.dist_sampler.HierarchicalRouting`) serves
    each host-unique id once and broadcasts the response back to every
    requesting slot — which is only bit-identical to the flat path if
    a given id draws the same positions regardless of where (and how
    often) it sits in the buffer.  Here each row derives its own key
    with ``fold_in(key, id)``; everything else (Floyd's structure, the
    duplicate test, the masks) mirrors :func:`_draw_positions` exactly.
    """
    b = deg.shape[0]
    slot_ids = jnp.arange(fanout, dtype=jnp.int32)  # [k]
    row_keys = jax.vmap(jax.random.fold_in, (None, 0))(
        key, jnp.where(seeds >= 0, seeds, 0).astype(jnp.int32))

    if with_replacement:
        pos = jax.vmap(
            lambda k, m: jax.random.randint(k, (fanout,), 0, m,
                                            dtype=jnp.int32)
        )(row_keys, jnp.maximum(deg, 1))
        mask = (slot_ids[None, :] < jnp.where(deg > 0, fanout, 0)[:, None])
        return pos, mask

    chosen = jnp.full((b, fanout), -1, jnp.int32)
    keys = jax.vmap(lambda k: jax.random.split(k, fanout))(row_keys)
    for i in range(fanout):
        j = deg - fanout + i                       # [B], >= 0 when deg > fanout
        t = jax.vmap(
            lambda k, m: jax.random.randint(k, (), 0, m, dtype=jnp.int32)
        )(keys[:, i], jnp.maximum(j + 1, 1))
        dup = jnp.any(chosen == t[:, None], axis=1)
        floyd_pos = jnp.where(dup, j, t)
        pos_i = jnp.where(deg > fanout, floyd_pos, i)
        chosen = chosen.at[:, i].set(pos_i)
    mask = slot_ids[None, :] < jnp.minimum(deg, fanout)[:, None]
    return chosen, mask


def draw_positions(deg: jnp.ndarray, fanout: int, key: jax.Array,
                   with_replacement: bool, seeds: jnp.ndarray,
                   key_by: str = "slot"):
    """Draw dispatcher shared by the XLA and Pallas paths.

    ``key_by='slot'`` is the historical per-(key, buffer slot) stream;
    ``key_by='id'`` keys per (key, seed id) so draws are invariant to
    request-buffer layout (required by hierarchical routing).
    """
    if key_by == "slot":
        return _draw_positions(deg, fanout, key, with_replacement)
    if key_by == "id":
        return _draw_positions_by_id(deg, fanout, key, with_replacement,
                                     seeds)
    raise ValueError(f"key_by must be 'slot' or 'id', got {key_by!r}")


def sample_neighbors(
    indptr: jnp.ndarray,
    indices: jnp.ndarray,
    seeds: jnp.ndarray,
    fanout: int,
    key: jax.Array,
    edge_ids: Optional[jnp.ndarray] = None,
    with_replacement: bool = False,
    with_edge: bool = True,
    force: str = "auto",
    key_by: str = "slot",
) -> NeighborOutput:
    """Sample up to ``fanout`` neighbors per seed from a CSR graph.

    Args:
      indptr: ``[N+1]`` CSR row pointers.
      indices: ``[E]`` CSR column (neighbor) ids.
      seeds: ``[B]`` seed node ids; negative entries are padding.
      fanout: static per-seed sample size. ``fanout == -1`` is not supported
        here (full expansion is :func:`glt_tpu.ops.subgraph.node_subgraph`).
      key: PRNG key; results are a pure function of (graph, seeds, key).
      edge_ids: optional ``[E]`` global edge ids; defaults to CSR positions,
        matching the reference's implicit edge ids.
      with_replacement: if True, draw i.i.d. uniform neighbors instead of a
        uniform subset.
      with_edge: when False, skip edge-id materialisation entirely
        (``eids`` is None) — saves one random gather over the edge array
        per hop, the dominant cost at wide frontiers (the reference's
        ``Sample`` vs ``SampleWithEdge`` split, random_sampler.cu:267,310).
      force: neighbor-read kernel seam — 'auto' | 'pallas' | 'xla' |
        'interpret' (see module docstring).  ``GLT_SAMPLE_FORCE``
        overrides.
      key_by: randomness keying — 'slot' (per buffer position, the
        historical stream) or 'id' (per seed id, layout-invariant; used
        by the hierarchical dedup-then-exchange transport so flat and
        hier routing stay bit-identical).

    Returns:
      :class:`NeighborOutput` with static ``[B, fanout]`` arrays.  Rows with
      ``degree <= fanout`` return the full (untruncated) neighbor list in CSR
      order, as the reference sampler does (random_sampler.cu:79-85).
    """
    if fanout <= 0:
        raise ValueError(f"fanout must be positive, got {fanout}")
    env = os.environ.get("GLT_SAMPLE_FORCE")
    if env in ("pallas", "xla", "interpret"):
        force = env
    seeds = seeds.astype(jnp.int32)
    if force != "xla":
        # Lazy import: sample_pallas imports the draw/offset helpers
        # from this module.
        from . import sample_pallas as _sp

        params = _sp.auto_params(seeds.shape[0], fanout, indices.dtype)
        if force in ("pallas", "interpret") or params is not None:
            return _sp.sample_neighbors_pallas(
                indptr, indices, seeds, fanout, key, edge_ids=edge_ids,
                with_replacement=with_replacement, with_edge=with_edge,
                params=params, interpret=(force == "interpret"),
                key_by=key_by)
    start, deg = _row_offsets_and_degrees(indptr, seeds)
    pos, mask = draw_positions(deg, fanout, key, with_replacement, seeds,
                               key_by=key_by)
    flat = start[:, None] + jnp.where(mask, pos, 0)
    nbrs = jnp.where(mask, indices[flat], PADDING_ID).astype(jnp.int32)
    if not with_edge:
        eids = None
    elif edge_ids is None:
        eids = jnp.where(mask, flat, PADDING_ID).astype(jnp.int32)
    else:
        eids = jnp.where(mask, edge_ids[flat], PADDING_ID).astype(jnp.int32)
    return NeighborOutput(nbrs=nbrs, eids=eids, mask=mask)


def lookup_degrees(indptr: jnp.ndarray, seeds: jnp.ndarray) -> jnp.ndarray:
    """Per-seed out-degree (cf. ``LookupDegreeKernel``, csrc/cuda/graph.cu:30)."""
    _, deg = _row_offsets_and_degrees(indptr, seeds.astype(jnp.int32))
    return deg
