"""Fused frontier dedup + feature gather: one dispatch, zero HBM bounce.

The unfused pipeline (:func:`~glt_tpu.ops.dedup_gather.dedup_gather_rows`)
materialises the ``[U, d]`` unique-row block in HBM and then re-reads it
for the scatter-back — two full passes over the frontier's feature bytes.
When the unique block fits VMEM there is no reason for it to ever touch
HBM: this kernel DMAs each unique row **once** from the feature table
into a VMEM-resident buffer and serves every duplicate position straight
out of that buffer, fusing dedup-gather and scatter-back into a single
``pallas_call``.

Division of labor (mirrors the sampling seam in sample_pallas.py):

* **ordering** stays in XLA — :func:`unique_first_occurrence` computes
  the first-occurrence unique ids and inverse permutation, the
  bit-identity anchor shared with the unfused path;
* **bytes** move in the kernel — phase A (grid step 0) streams the
  ``count`` live unique rows through a ring of per-row DMAs into the
  persistent VMEM buffer (scratch persists across sequential grid
  steps); phase B copies ``out[i] = buf[inverse[i]]`` per 256-row output
  chunk via dynamic-sublane loads.

The contract is the dedup_gather_rows contract, bit for bit: ``features``
matches ``where(ids >= 0, table[id2index[ids]], 0)`` exactly, so every
existing train/dist test doubles as a correctness oracle.  Frontiers
whose unique block exceeds the VMEM budget (or feature widths not a
multiple of 128 lanes) fall back to the unfused path — same bits, two
HBM passes.  ``GLT_FUSED_FORCE`` (``pallas``/``xla``/``interpret``)
overrides the seam; off-TPU ``auto`` resolves to the XLA path and
interpret mode keeps CPU tests hardware-free.
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tpu_limits
from ..store import quant
from .gather_pallas import gather_rows
from .unique import unique_first_occurrence

_CHUNK = 256
_LANE = tpu_limits.LANE
_SUBLANE = tpu_limits.SUBLANE_F32
# Sublane count of the packed scale/zero/k input block (== quant.
# SCALE_ZERO_ROWS, padded to the f32 tiling floor for GLT019).
_SZ_ROWS = 8
# Unique-block VMEM budget: 3/8 of the core's VMEM (~6 MB of 16) leaves
# headroom for the output chunk, double-buffered DMA metadata, and
# whatever the surrounding scanned step keeps live.  Derived from
# tpu_limits so the runtime gate (fused_frontier_supported) and the
# static model (analysis/kernelmodel.py GLT017) can never disagree.
DEFAULT_VMEM_BUDGET = tpu_limits.VMEM_BYTES * 3 // 8
_RING = 8

# Dimension domain for the static VMEM model.  The scratch buffer is
# [up, d] where both dims are runtime-sized but their PRODUCT is gated
# by fused_frontier_supported (up * d * itemsize <= DEFAULT_VMEM_BUDGET),
# so the model checks the gate's corner points jointly: at each feature
# width, the deepest unique block the runtime gate admits.
VMEM_MODEL_DOMAIN = {
    ("up", "d"): (
        (DEFAULT_VMEM_BUDGET // (tpu_limits.LANE * 4), tpu_limits.LANE),
        (DEFAULT_VMEM_BUDGET // (512 * 4), 512),
        (DEFAULT_VMEM_BUDGET // (tpu_limits.MODEL_MAX_LANES * 4),
         tpu_limits.MODEL_MAX_LANES),
    ),
}


class FusedFrontier(NamedTuple):
    """One-dispatch frontier: ids deduped and features gathered."""
    unique_ids: jnp.ndarray   # [B] first-occurrence unique ids, -1 padded
    inverse: jnp.ndarray      # [B] position -> unique slot, -1 at padding
    features: jnp.ndarray     # [B, d], bit-identical to dedup_gather_rows


def fused_frontier_supported(table: jnp.ndarray, ids: jnp.ndarray,
                             vmem_budget: Optional[int] = None) -> bool:
    """True when the unique block fits the VMEM budget and the feature
    width tiles the 128-lane register exactly (the fused kernel does
    whole-row DMAs/copies; odd widths go to the unfused path)."""
    budget = DEFAULT_VMEM_BUDGET if vmem_budget is None else int(vmem_budget)
    d = int(table.shape[1])
    up = -(-int(ids.shape[0]) // _SUBLANE) * _SUBLANE
    return d % _LANE == 0 and up * d * table.dtype.itemsize <= budget


def _make_fused_kernel(up: int, nbuf: int, chunk: int):
    def kernel(uid_ref, nu_ref, inv_ref, table_ref, out_ref, buf, sems):
        c = pl.program_id(0)

        # Phase A (first grid step only): stream the live unique rows
        # into the persistent VMEM buffer.  `buf` is scratch, which on
        # TPU persists across the sequential grid — later steps reuse
        # the rows filled here.
        @pl.when(c == 0)
        def _():
            nu = nu_ref[0]

            def dma(j):
                return pltpu.make_async_copy(
                    table_ref.at[pl.ds(uid_ref[j], 1)],
                    buf.at[pl.ds(j, 1)],
                    sems.at[lax.rem(j, nbuf)])

            for k in range(nbuf):
                @pl.when(k < nu)
                def _():
                    dma(k).start()

            def fill(j, carry):
                @pl.when(j < nu)
                def _():
                    dma(j).wait()

                @pl.when(j + nbuf < nu)
                def _():
                    dma(j + nbuf).start()

                return carry

            lax.fori_loop(0, up, fill, None)

        # Phase B (every grid step): serve this output chunk from the
        # buffer.  Dynamic-SUBLANE indexing (pl.ds over rows) is
        # supported; inv_ref is pre-clipped so padding rows read slot 0
        # harmlessly (the XLA epilogue zeroes them).
        def copy_row(s, carry):
            iv = inv_ref[c * chunk + s]
            row = pl.load(buf, (pl.ds(iv, 1), slice(None)))
            pl.store(out_ref, (pl.ds(s, 1), slice(None)), row)
            return carry

        lax.fori_loop(0, chunk, copy_row, None)

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret", "ring_depth"))
def _fused_gather(table, uidx, count, inv, interpret=False,
                  ring_depth=_RING):
    """[B, d] rows with ``out[i] = table[uidx[inv[i]]]`` for ``inv[i] >=
    0`` positions (padding rows carry garbage; caller zeroes them)."""
    b = inv.shape[0]
    d = table.shape[1]
    n = table.shape[0]
    up = -(-b // _SUBLANE) * _SUBLANE
    bp = -(-b // _CHUNK) * _CHUNK
    uid_p = jnp.concatenate(
        [jnp.clip(uidx.astype(jnp.int32), 0, n - 1),
         jnp.zeros((up - b,), jnp.int32)])
    inv_p = jnp.concatenate(
        [jnp.clip(inv.astype(jnp.int32), 0, up - 1),
         jnp.zeros((bp - b,), jnp.int32)])
    nu = jnp.asarray(count, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(bp // _CHUNK,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((_CHUNK, d), lambda c, *_: (c, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((up, d), table.dtype),
            pltpu.SemaphoreType.DMA((ring_depth,)),
        ],
    )
    out = pl.pallas_call(
        _make_fused_kernel(up, ring_depth, _CHUNK),
        out_shape=jax.ShapeDtypeStruct((bp, d), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(uid_p, nu, inv_p, table)
    return out[:b]


def _make_fused_dequant_kernel(up: int, nbuf: int, chunk: int, mode: str):
    """Fused kernel with a dequantize epilogue in phase B.

    Phase A streams COMPRESSED unique rows into the VMEM buffer (a bf16
    buffer holds 2x, an int8 buffer 4x the frontier of a raw f32 one —
    the VMEM gate in :func:`fused_frontier_supported` already counts
    storage bytes); each phase-B copy widens to f32 through the shared
    decode formulas of :func:`glt_tpu.store.quant.dequantize` (see the
    quant module docstring for why affine is add-then-mul).
    """

    def kernel(uid_ref, nu_ref, inv_ref, table_ref, sz_ref, out_ref,
               buf, sems):
        c = pl.program_id(0)
        scale = sz_ref[0:1, :]
        zero = sz_ref[1:2, :]
        kvec = sz_ref[2:3, :]

        @pl.when(c == 0)
        def _():
            nu = nu_ref[0]

            def dma(j):
                return pltpu.make_async_copy(
                    table_ref.at[pl.ds(uid_ref[j], 1)],
                    buf.at[pl.ds(j, 1)],
                    sems.at[lax.rem(j, nbuf)])

            for k in range(nbuf):
                @pl.when(k < nu)
                def _():
                    dma(k).start()

            def fill(j, carry):
                @pl.when(j < nu)
                def _():
                    dma(j).wait()

                @pl.when(j + nbuf < nu)
                def _():
                    dma(j + nbuf).start()

                return carry

            lax.fori_loop(0, up, fill, None)

        def copy_row(s, carry):
            iv = inv_ref[c * chunk + s]
            row = pl.load(buf, (pl.ds(iv, 1), slice(None)))
            row = row.astype(jnp.float32)
            if mode == "affine":
                row = jnp.where(scale > 0.0, (row + kvec) * scale, zero)
            pl.store(out_ref, (pl.ds(s, 1), slice(None)), row)
            return carry

        lax.fori_loop(0, chunk, copy_row, None)

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret", "mode",
                                             "ring_depth"))
def _fused_gather_dq(table, sz, uidx, count, inv, interpret=False,
                     mode="widen", ring_depth=_RING):
    """Dequantizing twin of :func:`_fused_gather`: compressed ``table``
    in, f32 rows out.  ``sz`` is the ``[_SZ_ROWS, d]`` f32
    scale/zero/k block."""
    b = inv.shape[0]
    d = table.shape[1]
    n = table.shape[0]
    up = -(-b // _SUBLANE) * _SUBLANE
    bp = -(-b // _CHUNK) * _CHUNK
    uid_p = jnp.concatenate(
        [jnp.clip(uidx.astype(jnp.int32), 0, n - 1),
         jnp.zeros((up - b,), jnp.int32)])
    inv_p = jnp.concatenate(
        [jnp.clip(inv.astype(jnp.int32), 0, up - 1),
         jnp.zeros((bp - b,), jnp.int32)])
    nu = jnp.asarray(count, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(bp // _CHUNK,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((_SZ_ROWS, d), lambda c, *_: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_CHUNK, d), lambda c, *_: (c, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((up, d), table.dtype),
            pltpu.SemaphoreType.DMA((ring_depth,)),
        ],
    )
    out = pl.pallas_call(
        _make_fused_dequant_kernel(up, ring_depth, _CHUNK, mode),
        out_shape=jax.ShapeDtypeStruct((bp, d), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(uid_p, nu, inv_p, table, sz)
    return out[:b]


def fused_frontier(table: jnp.ndarray, ids: jnp.ndarray,
                   id2index: Optional[jnp.ndarray] = None,
                   force: str = "auto",
                   vmem_budget: Optional[int] = None,
                   dequant=None) -> FusedFrontier:
    """Dedup + gather a frontier in one dispatch.

    Bit-identical to running :func:`unique_first_occurrence` +
    :func:`~glt_tpu.ops.dedup_gather.dedup_gather_rows` separately, on
    both the fused and fallback paths.

    Args:
      table: ``[N, d]`` feature rows.
      ids: ``[B]`` frontier ids, -1 padded.
      id2index: optional hotness indirection applied to unique ids.
      force: 'auto' | 'pallas' | 'xla' | 'interpret' — the fused-kernel
        seam; ``GLT_FUSED_FORCE`` env overrides.  'interpret' runs the
        kernel in Pallas interpret mode (CPU tests); 'pallas'/'interpret'
        still fall back to XLA when the frontier exceeds the VMEM budget.
      vmem_budget: unique-block byte budget (default ~6 MB).
      dequant: optional :class:`~glt_tpu.store.quant.QuantSpec` for a
        compressed ``table``.  The fused kernel buffers compressed
        unique rows (2x/4x frontier capacity under the same VMEM gate)
        and widens to f32 in the phase-B epilogue; the fallback
        dequantizes post-gather with the identical formula, so both
        arms still agree bit-for-bit.  Padding rows are zeroed AFTER
        dequantization (``dequantize(0) != 0`` for int8).
    """
    env = os.environ.get("GLT_FUSED_FORCE")
    if env in ("pallas", "xla", "interpret"):
        force = env
    ids = ids.astype(jnp.int32)
    uniq, inv, cnt = unique_first_occurrence(ids)
    uvalid = uniq >= 0
    uidx = jnp.where(uvalid, uniq, 0)
    if id2index is not None:
        uidx = jnp.take(id2index, uidx, axis=0, mode="clip")
    use = (force in ("pallas", "interpret")
           or (force == "auto" and jax.default_backend() == "tpu"))
    compressed = dequant is not None and dequant.is_compressed
    if use and fused_frontier_supported(table, ids, vmem_budget):
        if compressed:
            mode = "affine" if dequant.codec == "int8" else "widen"
            sz = jnp.asarray(
                quant.scale_zero_rows(dequant, int(table.shape[1])))
            rows = _fused_gather_dq(table, sz, uidx, cnt, inv,
                                    interpret=(force == "interpret"),
                                    mode=mode)
        else:
            rows = _fused_gather(table, uidx, cnt, inv,
                                 interpret=(force == "interpret"))
        x = jnp.where((inv >= 0)[:, None], rows, 0)
    else:
        # Unfused fallback — dedup_gather_rows verbatim (two HBM passes,
        # same bits).  inv only references valid unique slots (< cnt),
        # so both paths read identical source rows.
        urows = gather_rows(table, uidx, dequant=dequant)
        urows = jnp.where(uvalid[:, None], urows, 0)
        rows = jnp.take(urows, jnp.clip(inv, 0, inv.shape[0] - 1), axis=0)
        x = jnp.where((inv >= 0)[:, None], rows, 0)
    return FusedFrontier(unique_ids=uniq, inverse=inv, features=x)
