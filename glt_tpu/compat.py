"""JAX version compatibility shims.

The engine targets the modern spelling ``jax.shard_map(..., check_vma=)``
(JAX >= 0.6).  Older runtimes ship the same primitive as
``jax.experimental.shard_map.shard_map(..., check_rep=)`` — identical
semantics, different address and keyword.  :func:`install` bridges the
gap in whichever direction is needed so every caller (library, tests,
benchmarks) can use one spelling.

Imported for its side effect from ``glt_tpu/__init__`` — safe to import
multiple times, and a no-op when the running JAX already matches.
"""
from __future__ import annotations

import functools


def _wrap_check_vma(fn):
    """Adapt a legacy ``check_rep`` shard_map to the ``check_vma`` API."""

    @functools.wraps(fn)
    def shard_map(f=None, /, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return lambda g: shard_map(g, *args, **kwargs)
        return fn(f, *args, **kwargs)

    return shard_map


def install() -> None:
    """Ensure ``jax.shard_map`` exists and accepts ``check_vma=``."""
    import jax

    try:
        current = jax.shard_map
    except AttributeError:
        current = None
    if current is not None:
        # Modern JAX already accepts check_vma; nothing to do.
        import inspect

        try:
            params = inspect.signature(current).parameters
        except (TypeError, ValueError):
            params = {}
        if "check_vma" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()):
            return
        jax.shard_map = _wrap_check_vma(current)
        return
    from jax.experimental.shard_map import shard_map as legacy

    jax.shard_map = _wrap_check_vma(legacy)


install()
