"""Tiered feature store: HBM-resident hot rows + host-DRAM cold rows.

Rebuild of the reference's two-tier feature system (python/data/feature.py +
csrc/cuda/unified_tensor.cu): there, a ``split_ratio`` fraction of rows is
sharded across an NVLink clique's GPUs and the remainder is pinned host
memory read through UVA, with a warp-per-row gather kernel choosing the
source by binary-scanning shard offsets (unified_tensor.cu:35-81).

TPU redesign — no UVA, no IPC handles:

* the **hot tier** is a plain ``jax.Array`` in device HBM (sharding it
  across a mesh is the :mod:`glt_tpu.parallel` layer's job, the analog of
  the reference's ``DeviceGroup`` replication, feature.py:31-45);
* the **cold tier** stays in host numpy and is gathered eagerly on the
  host, overlapped with device compute by the loader's prefetch pipeline —
  the role UVA reads played on GPU (the TPU runtime in use does not support
  host callbacks inside jit, so the cold path is a host-side stage, exactly
  where the reference put its CPU fallback, feature.py:156);
* the ``id2index`` indirection (feature.py:141-154) is identical: lookups
  translate global ids through the hotness reordering of
  :func:`~glt_tpu.data.reorder.sort_by_in_degree`.

``gather`` is jit-safe when the store is fully device-resident
(``split_ratio == 1.0``); tiered stores gather eagerly with a static output
shape ``[B, d]``.  Padding ids (< 0) return zero rows either way.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class Feature:
    """Row-gatherable feature matrix with hot/cold tiering.

    Args:
      feature_array: ``[N, d]`` host array (already hotness-reordered if
        ``id2index`` is given).
      split_ratio: fraction of rows resident in device HBM (the rest stays
        on host).  1.0 = fully device-resident, 0.0 = fully host.
      id2index: optional ``[N]`` indirection from global id to row.
      dtype: optional cast applied to gathered rows (e.g. ``jnp.bfloat16``).
    """

    def __init__(
        self,
        feature_array: np.ndarray,
        split_ratio: float = 1.0,
        id2index: Optional[np.ndarray] = None,
        dtype=None,
    ):
        feature_array = np.asarray(feature_array)
        if feature_array.ndim == 1:
            feature_array = feature_array[:, None]
        self._n, self._dim = feature_array.shape
        self.split_ratio = float(split_ratio)
        self._hot_count = int(self._n * self.split_ratio)
        self.dtype = dtype or jnp.asarray(feature_array[:1]).dtype

        self._hot = jnp.asarray(feature_array[: self._hot_count], self.dtype)
        # Host tier; kept as a contiguous numpy view for fast np.take.
        self._cold = np.ascontiguousarray(feature_array[self._hot_count:])
        self._id2index = (
            None if id2index is None else jnp.asarray(id2index, jnp.int32))
        self._id2index_np = (
            None if id2index is None else np.asarray(id2index, np.int32))
        self._host_full = feature_array  # for cpu_get / save paths
        self._gather_jit = None

    @staticmethod
    def _gather_hot_impl(hot, id2index, ids):
        from ..ops.gather_pallas import gather_rows

        valid = ids >= 0
        idx = jnp.where(valid, ids, 0)
        if id2index is not None:
            idx = id2index[idx]
        # XLA gather (measured 2x the Pallas DMA kernel; see
        # ops/gather_pallas.py docstring).
        rows = gather_rows(hot, idx)
        return jnp.where(valid[:, None], rows, 0)

    # -- shape info --------------------------------------------------------
    @property
    def shape(self):
        return (self._n, self._dim)

    @property
    def size(self) -> int:
        return self._n

    @property
    def hot_count(self) -> int:
        return self._hot_count

    @property
    def id2index(self):
        return self._id2index

    @property
    def hot_rows(self) -> jnp.ndarray:
        """The HBM-resident hot tier ``[hot_count, d]`` as a jax.Array."""
        return self._hot

    # -- gather ------------------------------------------------------------
    def gather(self, ids: jnp.ndarray) -> jnp.ndarray:
        """Gather rows for global ``ids`` (-1 padded).

        Fully device-resident stores (``split_ratio == 1.0``) are jit-safe.
        Tiered stores run the hot gather on device and the cold gather on
        host, merging on device — callable only eagerly (the loader stages
        it before the jitted train step).  Padding rows are zeros.
        """
        if self._cold.shape[0] == 0:
            if isinstance(ids, jax.core.Tracer):
                # Already inside an enclosing jit: trace inline.
                return self._gather_hot_impl(self._hot, self._id2index,
                                             jnp.asarray(ids, jnp.int32))
            # Eager call sites (loader collate): ONE fused dispatch
            # instead of per-op dispatches (tunnel-latency bound).
            if self._gather_jit is None:
                self._gather_jit = jax.jit(self._gather_hot_impl)
            return self._gather_jit(self._hot, self._id2index,
                                    jnp.asarray(ids, jnp.int32))

        if isinstance(ids, jax.core.Tracer):
            raise ValueError(
                "tiered Feature.gather (split_ratio < 1) is a host-side "
                "stage and cannot run under jit; gather before the jitted "
                "step or use split_ratio=1.0")
        ids_np = np.asarray(ids).astype(np.int64)
        valid = ids_np >= 0
        idx = np.where(valid, ids_np, 0)
        if self._id2index_np is not None:
            idx = self._id2index_np[idx]
        is_hot = idx < self._hot_count
        cold_np = np.take(self._cold,
                          np.clip(np.where(is_hot, 0, idx - self._hot_count),
                                  0, max(self._cold.shape[0] - 1, 0)),
                          axis=0)
        cold_rows = jnp.asarray(cold_np, self.dtype)
        vmask = jnp.asarray(valid)[:, None]
        if self._hot_count == 0:
            # Fully host-resident (split_ratio == 0, e.g. a shared-memory
            # attach in a sampling worker): no device hot tier to gather.
            return jnp.where(vmask, cold_rows, 0)
        # Device gather for the hot rows, host gather for the cold rows.
        hot_rows = jnp.take(self._hot,
                            jnp.asarray(np.where(is_hot, idx, 0), jnp.int32),
                            axis=0, mode="clip")
        mask = jnp.asarray(is_hot & valid)[:, None]
        return jnp.where(mask, hot_rows, jnp.where(vmask, cold_rows, 0))

    def __getitem__(self, ids) -> jnp.ndarray:
        return self.gather(jnp.atleast_1d(jnp.asarray(ids)))

    def cpu_get(self, ids: np.ndarray) -> np.ndarray:
        """Pure host-side lookup (cf. feature.py:156 ``cpu_get``)."""
        ids = np.atleast_1d(np.asarray(ids))
        valid = ids >= 0
        idx = np.where(valid, ids, 0)
        if self._id2index is not None:
            idx = np.asarray(self._id2index)[idx]
        rows = self._host_full[idx]
        rows = np.where(valid[:, None], rows, 0)
        return rows

    def __len__(self) -> int:
        return self._n

    def __repr__(self) -> str:
        return (f"Feature(shape={self.shape}, split_ratio={self.split_ratio},"
                f" hot={self._hot_count})")
