"""Tiered feature store: HBM-resident hot rows + host-DRAM cold rows.

Rebuild of the reference's two-tier feature system (python/data/feature.py +
csrc/cuda/unified_tensor.cu): there, a ``split_ratio`` fraction of rows is
sharded across an NVLink clique's GPUs and the remainder is pinned host
memory read through UVA, with a warp-per-row gather kernel choosing the
source by binary-scanning shard offsets (unified_tensor.cu:35-81).

TPU redesign — no UVA, no IPC handles:

* the **hot tier** is a plain ``jax.Array`` in device HBM (sharding it
  across a mesh is the :mod:`glt_tpu.parallel` layer's job, the analog of
  the reference's ``DeviceGroup`` replication, feature.py:31-45);
* the **cold tier** stays in host numpy and is gathered eagerly on the
  host — and ONLY at the batch positions that actually resolve cold: the
  host moves ``n_cold`` rows, not ``B`` rows, and the hot/cold merge is a
  padded device scatter instead of a double full-batch materialization;
* an optional **cross-batch HBM cache** (:mod:`.feature_cache`) fronts the
  cold tier: recently fetched cold rows stay device-resident, so repeat
  lookups (hub nodes under power-law sampling) skip the host entirely —
  the TPU seat of the reference's ``UnifiedTensor`` hotness cache.  Enable
  with :meth:`Feature.enable_cold_cache`; hit/miss counters ride on device
  and surface through :meth:`Feature.cache_stats`.
* the ``id2index`` indirection (feature.py:141-154) is identical: lookups
  translate global ids through the hotness reordering of
  :func:`~glt_tpu.data.reorder.sort_by_in_degree`.

``gather`` is jit-safe when the store is fully device-resident
(``split_ratio == 1.0``); tiered stores gather eagerly with a static output
shape ``[B, d]``.  Padding ids (< 0) return zero rows either way.  With
``dedup=True`` device gathers route through
:func:`~glt_tpu.ops.dedup_gather.dedup_gather_rows` — bit-identical
output, each unique row fetched from HBM once.

Ids must fit int32 (GLT004): int64 id arrays are accepted but their
VALUES are range-checked before the cast — silent truncation raises
``OverflowError`` instead of corrupting the gather.
"""
from __future__ import annotations

import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .feature_cache import cache_init, cache_insert, cache_lookup

_I32_MAX = np.iinfo(np.int32).max
_I32_MIN = np.iinfo(np.int32).min


def require_int32_ids(ids) -> None:
    """GLT004 guard: refuse id VALUES that overflow int32.

    The whole engine runs int32 ids on device (x64 is disabled); a host
    int64 id array is fine as long as every value fits — otherwise the
    downcast silently truncates and the gather reads the wrong rows.
    Host-side check only (device arrays are already int32-typed; checking
    their values would force a sync).
    """
    if isinstance(ids, jax.core.Tracer) or isinstance(ids, jax.Array):
        return
    a = np.asarray(ids)
    if a.dtype.kind in "iu" and a.dtype.itemsize > 4 and a.size:
        mx, mn = int(a.max()), int(a.min())
        if mx > _I32_MAX or mn < _I32_MIN:
            raise OverflowError(
                f"node ids [{mn}, {mx}] overflow int32; the id space must "
                f"fit int32 (relabel/partition first — GLT004)")


def _pow2_pad(k: int) -> int:
    """Bucket a dynamic count to the next power of two (bounds the jit
    retrace count of the padded merge scatter to log2(B))."""
    return 1 if k <= 1 else 1 << (k - 1).bit_length()


class Feature:
    """Row-gatherable feature matrix with hot/cold tiering.

    Args:
      feature_array: ``[N, d]`` host array (already hotness-reordered if
        ``id2index`` is given).
      split_ratio: fraction of rows resident in device HBM (the rest stays
        on host).  1.0 = fully device-resident, 0.0 = fully host.
      id2index: optional ``[N]`` indirection from global id to row.
      dtype: optional cast applied to gathered rows (e.g. ``jnp.bfloat16``).
      dedup: route device gathers through the dedup-aware path (each
        unique row fetched once; output bit-identical to the naive
        gather).
    """

    def __init__(
        self,
        feature_array: np.ndarray,
        split_ratio: float = 1.0,
        id2index: Optional[np.ndarray] = None,
        dtype=None,
        dedup: bool = False,
    ):
        feature_array = np.asarray(feature_array)
        if feature_array.ndim == 1:
            feature_array = feature_array[:, None]
        self._n, self._dim = feature_array.shape
        self.split_ratio = float(split_ratio)
        self._hot_count = int(self._n * self.split_ratio)
        self.dtype = dtype or jnp.asarray(feature_array[:1]).dtype
        self.dedup = bool(dedup)

        self._quant = None               # compressed stores only (from_store)
        self._hot = jnp.asarray(feature_array[: self._hot_count], self.dtype)
        # Host tier; kept as a contiguous numpy view for fast np.take.
        self._cold = np.ascontiguousarray(feature_array[self._hot_count:])
        self._cold_count = self._cold.shape[0]
        self._cold_np_dtype = self._cold.dtype
        self._id2index = (
            None if id2index is None else jnp.asarray(id2index, jnp.int32))
        self._id2index_np = (
            None if id2index is None else np.asarray(id2index, np.int32))
        self._host_full = feature_array  # for cpu_get / save paths
        self._store = None               # optional disk tier (glt_tpu.store)
        self._stager = None
        self.bytes_from_hbm = 0          # hot-tier bytes served (tiered path)
        self._gather_jit = None          # device-array ids (no donation)
        self._gather_jit_host = None     # host ids: fresh buffer, donated
        self._cache = None               # optional cold-tier HBM cache
        self._cache_lookup_jit = None
        self._merge_cached_jit = None
        self._merge_jit = None

    @classmethod
    def from_store(cls, store, dram_budget_bytes: int,
                   split_ratio: float = 0.0,
                   id2index: Optional[np.ndarray] = None,
                   dtype=None, dedup: bool = False,
                   stage_threads: int = 1,
                   prefetch_scores: Optional[np.ndarray] = None
                   ) -> "Feature":
        """Third-tier constructor: features live on disk, never fully in
        DRAM (docs/storage.md).

        The ``split_ratio`` prefix loads to HBM once (straight from the
        store); every other row is served by a
        :class:`~glt_tpu.store.stager.DramStager` under the given
        (enforced) DRAM budget — cold gathers are bit-identical to the
        all-DRAM :class:`Feature`, only their residency differs.
        ``prefetch_scores`` (e.g. :func:`~glt_tpu.partition.
        frequency_partitioner.residency_scores` over the partition
        book's access statistics) warms the stager's DRAM set.

        A COMPRESSED store (``store.codec`` bf16/int8) keeps compressed
        bytes in every tier — the HBM hot prefix, the stager's DRAM
        buffer (whose row budget therefore stretches 2x/4x) and the
        device transfer — and dequantizes on-chip in the gather
        epilogue; ``self.dtype`` is then the LOGICAL dtype gathers
        return (f32), not the wire dtype.
        """
        from ..store.stager import DramStager

        self = cls.__new__(cls)
        self._n, self._dim = store.num_rows, store.dim
        self.split_ratio = float(split_ratio)
        self._hot_count = int(self._n * self.split_ratio)
        hot_np = store.read_rows(np.arange(self._hot_count, dtype=np.int64))
        spec = store.quant_spec() if hasattr(store, "quant_spec") else None
        self._quant = spec if (spec is not None and spec.is_compressed) \
            else None
        self.dedup = bool(dedup)
        if self._quant is not None:
            self.dtype = dtype or jnp.asarray(
                np.zeros(1, np.dtype(self._quant.logical_dtype))).dtype
            # storage-dtype hot tier (explicit dtype: rows, not ids)
            self._hot = jnp.asarray(hot_np, hot_np.dtype)
        else:
            self.dtype = dtype or jnp.asarray(np.zeros(1, store.dtype)).dtype
            self._hot = jnp.asarray(hot_np, self.dtype)
        self._cold = None                # no DRAM copy of the cold tier
        self._cold_count = self._n - self._hot_count
        self._cold_np_dtype = store.dtype
        self._id2index = (
            None if id2index is None else jnp.asarray(id2index, jnp.int32))
        self._id2index_np = (
            None if id2index is None else np.asarray(id2index, np.int32))
        self._host_full = None           # cpu_get reads the store directly
        self._store = store
        self._stager = DramStager(store, dram_budget_bytes,
                                  stage_threads=stage_threads)
        if prefetch_scores is not None and self._cold_count:
            scores = np.zeros(self._n, np.float64)
            scores[:] = np.asarray(prefetch_scores, np.float64)
            scores[: self._hot_count] = 0.0   # hot prefix never staged
            self._stager.warm(scores)
        self.bytes_from_hbm = 0
        self._gather_jit = None
        self._gather_jit_host = None
        self._cache = None
        self._cache_lookup_jit = None
        self._merge_cached_jit = None
        self._merge_jit = None
        return self

    def _fetch_cold(self, local_ids: np.ndarray) -> np.ndarray:
        """Cold rows by LOCAL id (0 = first cold row) — the tier seam:
        DRAM-resident numpy for plain features, DRAM-stage-or-disk for
        store-backed ones (bit-identical rows either way)."""
        if self._stager is not None:
            return self._stager.gather(
                np.asarray(local_ids, np.int64) + self._hot_count)
        return self._cold[local_ids]

    def stage_ahead(self, ids) -> None:
        """Hint upcoming global ``ids`` to the DRAM stager (async; no-op
        for DRAM-resident features).  The loader calls this at sample
        *dispatch* so staging overlaps the prefetch window."""
        if self._stager is None:
            return
        ids = np.asarray(ids).reshape(-1)
        ids = ids[ids >= 0].astype(np.int64)
        if self._id2index_np is not None:
            ids = self._id2index_np[ids].astype(np.int64)
        self._stager.stage_ahead(ids[ids >= self._hot_count])

    def store_stats(self) -> Optional[dict]:
        """Tier byte counters for store-backed features (``glt.store.*``
        seed): stager counters + this feature's hot-tier bytes."""
        if self._stager is None:
            return None
        stats = self._stager.stats()
        stats["bytes_from_hbm"] = self.bytes_from_hbm
        return stats

    def close(self) -> None:
        """Release the staging threads of a store-backed feature."""
        if self._stager is not None:
            self._stager.close()

    def _gather_hot_impl(self, hot, id2index, ids):
        from ..ops.dedup_gather import dedup_gather_rows
        from ..ops.gather_pallas import gather_rows
        from ..store import quant

        ids = ids.astype(jnp.int32)
        if self.dedup:
            # unique -> gather uniques -> scatter back (bit-identical).
            rows = dedup_gather_rows(hot, ids, id2index=id2index)
            if self._quant is not None:
                # Padding rows must be re-zeroed AFTER dequant:
                # dequantize(0) is the column zero point, not 0.
                rows = jnp.where((ids >= 0)[:, None],
                                 quant.dequantize(rows, self._quant), 0)
            return rows
        valid = ids >= 0
        idx = jnp.where(valid, ids, 0)
        if id2index is not None:
            idx = id2index[idx]
        rows = gather_rows(hot, idx, dequant=self._quant)
        return jnp.where(valid[:, None], rows, 0)

    # -- shape info --------------------------------------------------------
    @property
    def shape(self):
        return (self._n, self._dim)

    @property
    def size(self) -> int:
        return self._n

    @property
    def hot_count(self) -> int:
        return self._hot_count

    @property
    def id2index(self):
        return self._id2index

    @property
    def hot_rows(self) -> jnp.ndarray:
        """The HBM-resident hot tier ``[hot_count, d]`` as a jax.Array."""
        return self._hot

    # -- cold-tier cache ---------------------------------------------------
    def enable_cold_cache(self, capacity: int) -> None:
        """Attach a device-resident cache in front of the host cold tier.

        ``capacity`` rows of the cold tier stay resident in HBM (FIFO
        replacement); tiered ``gather`` calls then host-fetch only the
        cache MISSES.  Costs one device->host fetch of the ``[B]`` hit
        mask per gather (the host must know which rows to stage — the
        same sync the loader's overflow check already pays).

        A fully device-resident store (``split_ratio == 1.0``) has
        nothing to cache: the call warns and no-ops instead of failing.
        ``capacity`` above the cold-row count would only pad a cache no
        gather can ever fill past the cold tier itself, so it clamps
        (with a warning) to the cold-row count.
        """
        if self._cold_count == 0:
            warnings.warn(
                "enable_cold_cache is a no-op at split_ratio == 1.0: "
                "every row is already HBM-resident, there is no cold "
                "tier to cache", RuntimeWarning, stacklevel=2)
            return
        capacity = int(capacity)
        if capacity > self._cold_count:
            warnings.warn(
                f"cold-cache capacity {capacity} exceeds the "
                f"{self._cold_count}-row cold tier; clamping (a larger "
                f"cache can never hold more than every cold row)",
                RuntimeWarning, stacklevel=2)
            capacity = self._cold_count
        self._cache = cache_init(self._cold_count, capacity,
                                 self._dim, self.dtype)
        self._cache_lookup_jit = jax.jit(cache_lookup)

    def cache_stats(self) -> Optional[dict]:
        """Cold-cache hit/miss counters (host sync), or None."""
        if self._cache is None:
            return None
        from .feature_cache import cache_stats as _stats

        return _stats(self._cache)

    # -- gather ------------------------------------------------------------
    def gather(self, ids: jnp.ndarray) -> jnp.ndarray:
        """Gather rows for global ``ids`` (-1 padded).

        Fully device-resident stores (``split_ratio == 1.0``) are jit-safe.
        Tiered stores run the hot gather on device and the cold gather on
        host — touching each tier only at its own batch positions — and
        merge with a padded device scatter; callable only eagerly (the
        loader stages it before the jitted train step).  Padding rows are
        zeros.
        """
        if self._cold_count == 0:
            if isinstance(ids, jax.core.Tracer):
                # Already inside an enclosing jit: trace inline.
                return self._gather_hot_impl(self._hot, self._id2index,
                                             jnp.asarray(ids, jnp.int32))
            require_int32_ids(ids)
            # Eager call sites (loader collate): ONE fused dispatch
            # instead of per-op dispatches (tunnel-latency bound).  Host
            # ids arrive via a fresh device buffer that nothing else
            # references, so that buffer is donated; device-array ids
            # belong to the caller (e.g. ``out.node``, reused for the
            # label gather) and are NOT donated.
            donate = (not isinstance(ids, jax.Array)
                      and jax.default_backend() != "cpu")
            if not donate:
                if self._gather_jit is None:
                    self._gather_jit = jax.jit(self._gather_hot_impl)
                return self._gather_jit(self._hot, self._id2index,
                                        jnp.asarray(ids, jnp.int32))
            if self._gather_jit_host is None:
                self._gather_jit_host = jax.jit(self._gather_hot_impl,
                                                donate_argnums=(2,))
            return self._gather_jit_host(self._hot, self._id2index,
                                         jnp.asarray(ids, jnp.int32))

        if isinstance(ids, jax.core.Tracer):
            raise ValueError(
                "tiered Feature.gather (split_ratio < 1) is a host-side "
                "stage and cannot run under jit; gather before the jitted "
                "step or use split_ratio=1.0")
        require_int32_ids(ids)
        ids_np = np.asarray(ids).astype(np.int64)
        valid = ids_np >= 0
        idx = np.where(valid, ids_np, 0)
        if self._id2index_np is not None:
            idx = self._id2index_np[idx].astype(np.int64)
        is_hot = idx < self._hot_count
        hot_mask = valid & is_hot
        cold_mask = valid & ~is_hot
        if self._cache is not None:
            return self._gather_tiered_cached(idx, hot_mask, cold_mask)
        cold_pos = np.nonzero(cold_mask)[0]
        # Host moves ONLY the cold rows (was: full-batch np.take of both
        # tiers + masked merge).  Hot bytes count at the WIRE width — a
        # compressed hot tier serves compressed bytes.
        self.bytes_from_hbm += int(hot_mask.sum()) * self._dim \
            * jnp.dtype(self._hot.dtype).itemsize
        cold_np = self._fetch_cold(idx[cold_pos] - self._hot_count)
        cap = _pow2_pad(cold_pos.shape[0])
        b = ids_np.shape[0]
        pos_pad = np.full((cap,), b, np.int32)      # b = out-of-range: drop
        pos_pad[: cold_pos.shape[0]] = cold_pos
        rows_pad = np.zeros((cap, self._dim), self._cold_np_dtype)
        rows_pad[: cold_pos.shape[0]] = cold_np
        # Compressed rows cross the host->device wire at storage width
        # and widen inside the jitted merge; raw rows cast to the target
        # dtype host-side as before.
        rows_dev = (jnp.asarray(rows_pad) if self._quant is not None
                    else jnp.asarray(rows_pad, self.dtype))
        return self._merge_tiered(
            jnp.asarray(np.where(hot_mask, idx, 0), jnp.int32),
            jnp.asarray(hot_mask), jnp.asarray(pos_pad), rows_dev)

    def _merge_tiered(self, idx, hot_mask, cold_pos, cold_rows):
        """Device merge: hot gather at hot slots + cold-row scatter."""
        if self._merge_jit is None:
            from ..store import quant

            spec = self._quant

            @jax.jit
            def merge(hot, idx, hot_mask, cold_pos, cold_rows):
                if spec is not None:
                    cold_rows = quant.dequantize(cold_rows, spec)
                if hot.shape[0]:
                    rows = jnp.take(hot, idx, axis=0, mode="clip")
                    if spec is not None:
                        rows = quant.dequantize(rows, spec)
                    out = jnp.where(hot_mask[:, None], rows, 0)
                else:
                    # Fully host-resident (split_ratio == 0, e.g. a
                    # shared-memory attach in a sampling worker).
                    out = jnp.zeros((idx.shape[0], cold_rows.shape[1]),
                                    cold_rows.dtype)
                return out.at[cold_pos].set(cold_rows, mode="drop")

            self._merge_jit = merge
        return self._merge_jit(self._hot, idx, hot_mask, cold_pos,
                               cold_rows)

    def _gather_tiered_cached(self, idx, hot_mask, cold_mask):
        """Tiered gather with the HBM cold cache in front of the host.

        One device->host sync (the hit mask); the host stages only cache
        misses, and the merge program inserts them into the cache for the
        next batch (the previous cache buffers are donated in place).
        """
        b = idx.shape[0]
        cold_ids = np.where(cold_mask, idx - self._hot_count, -1).astype(
            np.int32)
        cold_ids_dev = jnp.asarray(cold_ids)
        rows_c, hit = self._cache_lookup_jit(self._cache, cold_ids_dev)
        hit_np = np.asarray(hit)                      # the one sync
        miss_mask = cold_mask & ~hit_np
        miss_pos = np.nonzero(miss_mask)[0]
        self.bytes_from_hbm += int(hot_mask.sum()) * self._dim \
            * jnp.dtype(self._hot.dtype).itemsize
        miss_np = self._fetch_cold(idx[miss_pos] - self._hot_count)
        cap = _pow2_pad(miss_pos.shape[0])
        pos_pad = np.full((cap,), b, np.int32)
        pos_pad[: miss_pos.shape[0]] = miss_pos
        rows_pad = np.zeros((cap, self._dim), self._cold_np_dtype)
        rows_pad[: miss_pos.shape[0]] = miss_np

        if self._merge_cached_jit is None:
            from ..store import quant

            spec = self._quant

            @jax.jit
            def merge_cached(cache, hot, idx, hot_mask, rows_c, hit,
                             cold_ids, miss_mask, cold_pos, cold_rows):
                # The cold cache stores POST-dequant logical rows, so
                # only the freshly staged misses widen here.
                if spec is not None:
                    cold_rows = quant.dequantize(cold_rows, spec)
                if hot.shape[0]:
                    rows = jnp.take(hot, idx, axis=0, mode="clip")
                    if spec is not None:
                        rows = quant.dequantize(rows, spec)
                    out = jnp.where(hot_mask[:, None], rows, 0)
                else:
                    out = jnp.zeros((idx.shape[0], rows_c.shape[1]),
                                    rows_c.dtype)
                out = jnp.where(hit[:, None], rows_c.astype(out.dtype), out)
                out = out.at[cold_pos].set(cold_rows.astype(out.dtype),
                                           mode="drop")
                # Insert the staged miss rows; out at miss positions holds
                # exactly the host-fetched cold rows.
                cache = cache_insert(
                    cache, jnp.where(miss_mask, cold_ids, -1), out,
                    miss_mask)
                cache = cache._replace(
                    hits=cache.hits + jnp.sum(hit.astype(jnp.int32)),
                    misses=cache.misses
                    + jnp.sum(miss_mask.astype(jnp.int32)))
                return cache, out

            self._merge_cached_jit = merge_cached

        rows_dev = (jnp.asarray(rows_pad) if self._quant is not None
                    else jnp.asarray(rows_pad, self.dtype))
        self._cache, out = self._merge_cached_jit(
            self._cache, self._hot,
            jnp.asarray(np.where(hot_mask, idx, 0), jnp.int32),
            jnp.asarray(hot_mask), rows_c, hit, cold_ids_dev,
            jnp.asarray(miss_mask), jnp.asarray(pos_pad), rows_dev)
        return out

    def __getitem__(self, ids) -> jnp.ndarray:
        return self.gather(jnp.atleast_1d(jnp.asarray(ids)))

    def cpu_get(self, ids: np.ndarray) -> np.ndarray:
        """Pure host-side lookup (cf. feature.py:156 ``cpu_get``).

        Store-backed features (:meth:`from_store`) read the rows straight
        off the disk store — no full DRAM materialization exists to index
        — bypassing the stager so inspection reads never churn the
        residency set.
        """
        require_int32_ids(ids)
        ids = np.atleast_1d(np.asarray(ids))
        valid = ids >= 0
        idx = np.where(valid, ids, 0)
        if self._id2index is not None:
            idx = np.asarray(self._id2index)[idx]
        if self._host_full is None:
            rows = self._store.read_rows(np.asarray(idx, np.int64))
            if self._quant is not None:
                from ..store import quant

                # Host decode mirrors the device formula; padding rows
                # re-zero below (decode(0) != 0 for int8).
                rows = quant.decode(rows, self._quant)
        else:
            rows = self._host_full[idx]
        rows = np.where(valid[:, None], rows, 0)
        return rows

    def __len__(self) -> int:
        return self._n

    def __repr__(self) -> str:
        return (f"Feature(shape={self.shape}, split_ratio={self.split_ratio},"
                f" hot={self._hot_count})")
