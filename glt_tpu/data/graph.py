"""Device-resident graph storage.

Rebuild of the reference's ``Graph`` (python/data/graph.py:124-239 +
csrc/cuda/graph.cu).  The CUDA version has three residency modes — CPU,
ZERO_COPY (pinned host memory read over UVA) and CUDA/DMA (full HBM copy).
The TPU analogues are:

* ``'DEVICE'`` — CSR arrays live in TPU HBM as jax Arrays (≈ DMA mode);
* ``'HOST'``   — CSR stays in host numpy; sampling runs on CPU backend or
  the arrays stream to device per call (≈ CPU mode).

ZERO_COPY has no TPU equivalent (no UVA); its role — graphs larger than one
device — is covered by sharding the graph across a mesh instead
(:mod:`glt_tpu.parallel`).  Lazy init mirrors ``Graph.lazy_init``
(data/graph.py:160-188).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .topology import CSRTopo

_MODES = ("DEVICE", "HOST")


class Graph:
    """CSR graph with lazily materialised device arrays.

    Args:
      topo: host :class:`CSRTopo`.
      mode: 'DEVICE' (HBM-resident) or 'HOST'.
      with_sorted_columns: also build a column-sorted CSR view used by the
        strict negative sampler's binary search
        (csrc/cuda/random_negative_sampler.cu:37-54).
    """

    def __init__(self, topo: CSRTopo, mode: str = "DEVICE",
                 with_sorted_columns: bool = False):
        mode = mode.upper()
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
        self.topo = topo
        self.mode = mode
        self._indptr: Optional[jnp.ndarray] = None
        self._indices: Optional[jnp.ndarray] = None
        self._edge_ids: Optional[jnp.ndarray] = None
        self._sorted_indices: Optional[jnp.ndarray] = None
        self._with_sorted_columns = with_sorted_columns
        self._trivial_edge_ids: Optional[bool] = None

    # -- lazy init (cf. data/graph.py:160-188) -----------------------------
    def lazy_init(self) -> None:
        if self._indptr is not None:
            return
        # ensure_compile_time_eval: materialisation must stay eager even when
        # a Graph property is first touched inside a jit trace — otherwise
        # tracers would be cached on the object and leak.
        with jax.ensure_compile_time_eval():
            as_arr = jnp.asarray if self.mode == "DEVICE" else np.asarray
            # copy=False: already-int32 arrays (e.g. shared-memory
            # attaches) stay views instead of per-process copies.
            self._indptr = as_arr(self.topo.indptr.astype(np.int32,
                                                          copy=False))
            self._indices = as_arr(self.topo.indices.astype(np.int32,
                                                            copy=False))
            host_eids = self.topo.edge_ids.astype(np.int32, copy=False)
            self._edge_ids = as_arr(host_eids)
            # Trivial (positional) edge ids need no gather at sample time:
            # the sampler can emit CSR positions directly, skipping one
            # random read over the edge array per hop.
            self._trivial_edge_ids = bool(
                host_eids.shape[0] == 0
                or (host_eids[0] == 0
                    and host_eids[-1] == host_eids.shape[0] - 1
                    and np.array_equal(
                        host_eids,
                        np.arange(host_eids.shape[0], dtype=np.int32))))
            if self._with_sorted_columns:
                srt = _sort_columns_within_rows(self.topo.indptr, self.topo.indices)
                self._sorted_indices = as_arr(srt.astype(np.int32))

    @property
    def indptr(self) -> jnp.ndarray:
        self.lazy_init()
        return self._indptr

    @property
    def indices(self) -> jnp.ndarray:
        self.lazy_init()
        return self._indices

    @property
    def edge_ids(self) -> jnp.ndarray:
        self.lazy_init()
        return self._edge_ids

    @property
    def gather_edge_ids(self) -> Optional[jnp.ndarray]:
        """Edge-id array for samplers, or None when ids are positional
        (the sampler then emits CSR positions without a gather)."""
        self.lazy_init()
        return None if self._trivial_edge_ids else self._edge_ids

    @property
    def sorted_indices(self) -> jnp.ndarray:
        if not self._with_sorted_columns:
            self._with_sorted_columns = True
            self._indptr = None  # force rebuild including the sorted view
        self.lazy_init()
        return self._sorted_indices

    @property
    def num_nodes(self) -> int:
        return self.topo.num_nodes

    @property
    def num_edges(self) -> int:
        return self.topo.num_edges

    def __repr__(self) -> str:
        return (f"Graph(num_nodes={self.num_nodes}, num_edges={self.num_edges},"
                f" mode={self.mode!r})")


def _sort_columns_within_rows(indptr: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Sort neighbor ids within each CSR row (host-side, one-time prep)."""
    row = np.repeat(np.arange(indptr.shape[0] - 1), np.diff(indptr))
    order = np.lexsort((indices, row))
    return np.asarray(indices)[order]
