"""TableDataset — graph/features from tabular storage (gated).

Mirrors ``graphlearn_torch/python/data/table_dataset.py:30-162``: the
reference reads ODPS/MaxCompute tables through the PAI-only ``common_io``
package.  That platform dependency does not exist here; this module keeps
the same API shape and gates on the reader being available, and adds a
generic columnar path (parquet/npz via numpy) so table-style ingestion
works without the proprietary reader.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .dataset import Dataset


def resolve_reader_factory(reader_factory):
    """Return ``(factory, out_of_range_exceptions)`` for table draining.

    Defaults to the PAI ``common_io`` reader (gated — the reference's only
    backend, data/table_dataset.py:30-162); any object with
    ``read(batch_size, allow_smaller_final_batch=True)`` + ``close()``
    works in its place.
    """
    if reader_factory is None:
        try:
            import common_io
        except ImportError as e:
            raise ImportError(
                "table reading without reader_factory needs the PAI "
                "'common_io' reader; pass reader_factory=... (any object "
                "with read()/close()) elsewhere") from e
        return (common_io.table.TableReader,
                (StopIteration, common_io.exception.OutOfRangeException))
    try:
        import common_io
        return reader_factory, (StopIteration,
                                common_io.exception.OutOfRangeException)
    except ImportError:
        return reader_factory, (StopIteration,)


def drain_table(table, reader_factory, oor, batch_size: int = 1024):
    """Read every record of ``table`` through the reader protocol."""
    reader = reader_factory(table)
    records = []
    try:
        while True:
            try:
                got = reader.read(batch_size,
                                  allow_smaller_final_batch=True)
            except oor:
                break
            if not got:
                break
            records.extend(got)
    finally:
        reader.close()
    return records


def parse_feature_field(field) -> list:
    """Decode a ``"f1:f2:...:fd"`` feature string (str or bytes —
    table_dataset.py:124-135 in the reference)."""
    if isinstance(field, bytes):
        field = field.decode()
    return [float(v) for v in field.split(":")]


class TableDataset(Dataset):
    """Build a Dataset from edge/node tables.

    ``from_arrays`` is the generic columnar path; ``from_odps`` mirrors the
    reference's entry point and raises unless a ``common_io``-compatible
    reader is importable.
    """

    @classmethod
    def from_arrays(
        cls,
        edge_src: np.ndarray,
        edge_dst: np.ndarray,
        node_ids: Optional[np.ndarray] = None,
        node_feat: Optional[np.ndarray] = None,
        node_label: Optional[np.ndarray] = None,
        graph_mode: str = "DEVICE",
        split_ratio: float = 1.0,
    ) -> "TableDataset":
        num_nodes = None
        if node_ids is not None:
            num_nodes = int(np.max(node_ids)) + 1
        ds = cls()
        ds.init_graph(np.stack([np.asarray(edge_src), np.asarray(edge_dst)]),
                      graph_mode=graph_mode, num_nodes=num_nodes)
        if node_feat is not None:
            feat = np.asarray(node_feat)
            if node_ids is not None:
                full = np.zeros((num_nodes, feat.shape[1]), feat.dtype)
                full[np.asarray(node_ids)] = feat
                feat = full
            ds.init_node_features(feat, split_ratio=split_ratio)
        if node_label is not None:
            lab = np.asarray(node_label)
            if node_ids is not None:
                full = np.full(num_nodes, -1, lab.dtype)
                full[np.asarray(node_ids)] = lab
                lab = full
            ds.init_node_labels(lab)
        return ds

    @classmethod
    def from_tables(
        cls,
        edge_tables,
        node_tables,
        reader_factory=None,
        graph_mode: str = "DEVICE",
        split_ratio: float = 1.0,
        label_from_last_column: bool = False,
        reader_batch_size: int = 1024,
        **graph_kwargs,
    ) -> "TableDataset":
        """Build a Dataset by draining table readers (cf. the reference's
        ``TableDataset.load``, data/table_dataset.py:28-148).

        Record formats mirror the reference exactly:
          * edge tables yield ``(src_id, dst_id)`` records;
          * node tables yield ``(id, "f1:f2:...:fd")`` records — the
            colon-separated feature string may be ``str`` or ``bytes``
            (table_dataset.py:124-135); with ``label_from_last_column``
            the final component is split off as an integer label.

        ``reader_factory(table_name) -> reader`` must return an object
        with ``read(batch_size, allow_smaller_final_batch=True)`` that
        raises ``StopIteration`` (or common_io's OutOfRangeException)
        when drained, and ``close()`` — the ``common_io.table.TableReader``
        interface.  Defaults to common_io (PAI platform, gated); pass
        your own factory anywhere else (see ``ListTableReader`` in
        tests/test_aux.py for the in-memory shape).

        Single-entry dicts build a homogeneous dataset; multi-entry
        dicts (keyed by edge type tuple / node type) build hetero.
        """
        reader_factory, oor = resolve_reader_factory(reader_factory)

        def drain(table):
            return drain_table(table, reader_factory, oor,
                               reader_batch_size)

        edge_hetero = len(edge_tables) > 1
        node_hetero = len(node_tables) > 1
        if edge_hetero != node_hetero:
            raise ValueError(
                f"edge_tables ({len(edge_tables)}) and node_tables "
                f"({len(node_tables)}) must agree on hetero-ness: a homo "
                f"graph with per-type features (or vice versa) is not a "
                f"consistent Dataset")
        edge_index = {}
        for e_type, table in edge_tables.items():
            recs = drain(table)
            arr = np.stack([
                np.array([r[0] for r in recs], dtype=np.int64),
                np.array([r[1] for r in recs], dtype=np.int64)])
            edge_index[e_type] = arr
        if not edge_hetero:
            edge_index = next(iter(edge_index.values()))

        feats, labels = {}, {}
        for n_type, table in node_tables.items():
            recs = drain(table)
            ids = np.array([r[0] for r in recs], dtype=np.int64)

            mat = np.asarray([parse_feature_field(r[1]) for r in recs],
                             np.float32)
            # Rows are stored BY ID so the graph's raw ids index them
            # directly; gaps get zero features / -1 labels (the reference
            # sorts by id and assumes contiguity, table_dataset.py:126 —
            # scattering by id is the gap-safe generalisation, matching
            # from_arrays).
            n_rows = int(ids.max()) + 1 if ids.size else 0
            full = np.zeros((n_rows, mat.shape[1]), np.float32)
            full[ids] = mat
            if label_from_last_column:
                lab = np.full(n_rows, -1, np.int64)
                lab[ids] = full[ids][:, -1].astype(np.int64)
                labels[n_type] = lab
                full = full[:, :-1]
            feats[n_type] = full
        if not node_hetero:
            feats = next(iter(feats.values()))
            labels = next(iter(labels.values())) if labels else None

        ds = cls()
        ds.init_graph(edge_index, graph_mode=graph_mode, **graph_kwargs)
        ds.init_node_features(feats, split_ratio=split_ratio)
        if label_from_last_column:
            ds.init_node_labels(labels)
        return ds

    @classmethod
    def from_odps(cls, edge_table: str, node_table: str, **kwargs):
        """Reference-named entry point: homo graph from two ODPS tables
        via the PAI ``common_io`` reader (gated; see :meth:`from_tables`)."""
        return cls.from_tables({"edge": edge_table}, {"node": node_table},
                               **kwargs)
