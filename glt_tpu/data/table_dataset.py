"""TableDataset — graph/features from tabular storage (gated).

Mirrors ``graphlearn_torch/python/data/table_dataset.py:30-162``: the
reference reads ODPS/MaxCompute tables through the PAI-only ``common_io``
package.  That platform dependency does not exist here; this module keeps
the same API shape and gates on the reader being available, and adds a
generic columnar path (parquet/npz via numpy) so table-style ingestion
works without the proprietary reader.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .dataset import Dataset


class TableDataset(Dataset):
    """Build a Dataset from edge/node tables.

    ``from_arrays`` is the generic columnar path; ``from_odps`` mirrors the
    reference's entry point and raises unless a ``common_io``-compatible
    reader is importable.
    """

    @classmethod
    def from_arrays(
        cls,
        edge_src: np.ndarray,
        edge_dst: np.ndarray,
        node_ids: Optional[np.ndarray] = None,
        node_feat: Optional[np.ndarray] = None,
        node_label: Optional[np.ndarray] = None,
        graph_mode: str = "DEVICE",
        split_ratio: float = 1.0,
    ) -> "TableDataset":
        num_nodes = None
        if node_ids is not None:
            num_nodes = int(np.max(node_ids)) + 1
        ds = cls()
        ds.init_graph(np.stack([np.asarray(edge_src), np.asarray(edge_dst)]),
                      graph_mode=graph_mode, num_nodes=num_nodes)
        if node_feat is not None:
            feat = np.asarray(node_feat)
            if node_ids is not None:
                full = np.zeros((num_nodes, feat.shape[1]), feat.dtype)
                full[np.asarray(node_ids)] = feat
                feat = full
            ds.init_node_features(feat, split_ratio=split_ratio)
        if node_label is not None:
            lab = np.asarray(node_label)
            if node_ids is not None:
                full = np.full(num_nodes, -1, lab.dtype)
                full[np.asarray(node_ids)] = lab
                lab = full
            ds.init_node_labels(lab)
        return ds

    @classmethod
    def from_odps(cls, edge_table: str, node_table: str, **kwargs):
        try:
            import common_io  # noqa: F401  (PAI platform only)
        except ImportError as e:
            raise ImportError(
                "TableDataset.from_odps requires the PAI 'common_io' "
                "reader, which is not available in this environment; use "
                "TableDataset.from_arrays with columns loaded via your own "
                "reader instead") from e
        raise NotImplementedError(
            "ODPS table reading is platform-specific; wire common_io "
            "readers to from_arrays columns")
