"""Cross-process zero-copy dataset sharing over POSIX shared memory.

Rebuild of the reference's IPC story: there, ``Graph`` re-registers from a
shared ``CSRTopo`` through a ``ForkingPickler`` hook (data/graph.py:
190-239), ``Feature`` ships CUDA-IPC handles and lazily rebuilds
(feature.py:208-258), and ``examples/feature_mp.py`` demonstrates a
feature store shared with worker processes.  On a TPU host the sharable
tier is host DRAM, so the mechanism is ``multiprocessing.shared_memory``:
``share_dataset`` copies each host array into a named shm segment once,
and the returned handle pickles to a few hundred bytes — mp sampling
workers ``attach_dataset`` and map the same physical pages instead of
rebuilding (or copying) the graph + features per process.  For a
papers100M-scale cold tier this is the difference between one copy and
``num_workers`` copies.

Usage with the worker-mode loaders (the handle rides the existing
picklable dataset_builder mechanism)::

    handle = share_dataset(ds)            # once, in the trainer
    loader = DistNeighborLoader(
        [15, 10, 5], seeds,
        dataset_builder=attach_dataset, builder_args=(handle,),
        worker_options=MpSamplingWorkerOptions(num_workers=4))
    ...
    handle.unlink()                       # after the last epoch

The creator owns the segments: ``handle.unlink()`` (or process exit via
the registered finalizer) frees them; attached processes just unmap.
"""
from __future__ import annotations

import atexit
import secrets
import threading
from multiprocessing import shared_memory
from typing import Optional

import numpy as np

from .dataset import Dataset
from .feature import Feature
from .graph import Graph
from .topology import CSRTopo

# Serializes the pre-3.13 register-suppression window in SharedArray.attach.
_attach_lock = threading.Lock()


class SharedArray:
    """A numpy array whose buffer lives in a named shm segment.

    Picklable: the pickle carries ``(name, shape, dtype)`` only; the
    receiving process attaches to the same physical pages.
    """

    def __init__(self, shm: shared_memory.SharedMemory, shape, dtype,
                 owner: bool):
        self._shm = shm
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self._owner = owner
        self.array = np.ndarray(self.shape, self.dtype, buffer=shm.buf)

    @classmethod
    def from_array(cls, arr: np.ndarray) -> "SharedArray":
        arr = np.ascontiguousarray(arr)
        name = f"glt_{secrets.token_hex(8)}"
        shm = shared_memory.SharedMemory(name=name, create=True,
                                         size=max(arr.nbytes, 1))
        out = cls(shm, arr.shape, arr.dtype, owner=True)
        out.array[...] = arr
        return out

    @classmethod
    def attach(cls, name: str, shape, dtype) -> "SharedArray":
        try:
            # 3.13+: do not register with this process's resource_tracker
            # — attachers must never unlink the creator's segment.
            shm = shared_memory.SharedMemory(name=name, track=False)
        except TypeError:
            # Pre-3.13 SharedMemory always registers attaches with the
            # resource tracker, so an attacher's exit would unlink the
            # creator's segment (and spam leaked-shm warnings).  Suppress
            # the register call itself — unregistering *after* the fact
            # would instead delete the creator's entry whenever both
            # processes share one tracker daemon (mp children do).  The
            # creator owns cleanup (handle.unlink / its atexit finalizer).
            from multiprocessing import resource_tracker

            seg = name if name.startswith("/") else "/" + name

            with _attach_lock:
                orig = resource_tracker.register

                def _skip_ours(rname, rtype, _orig=orig, _seg=seg):
                    # Scoped: only this segment's registration is dropped;
                    # unrelated resources other threads create during the
                    # window keep normal tracking.
                    if rtype == "shared_memory" and rname == _seg:
                        return None
                    return _orig(rname, rtype)

                resource_tracker.register = _skip_ours
                try:
                    shm = shared_memory.SharedMemory(name=name)
                finally:
                    resource_tracker.register = orig
        return cls(shm, shape, dtype, owner=False)

    @property
    def name(self) -> str:
        return self._shm.name

    def __reduce__(self):
        # Ship the np.dtype object itself: dtype.str does not round-trip
        # ml_dtypes (np.dtype(bfloat16).str == '<V2', which reconstructs
        # as void); np.dtype instances pickle correctly for all of them.
        return (SharedArray.attach,
                (self._shm.name, self.shape, self.dtype))

    def close(self) -> None:
        """Unmap; the owner also frees the segment."""
        try:
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except FileNotFoundError:
            pass

    def __repr__(self) -> str:
        return (f"SharedArray(name={self._shm.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, owner={self._owner})")


def _share(arr: Optional[np.ndarray]) -> Optional[SharedArray]:
    return None if arr is None else SharedArray.from_array(np.asarray(arr))


class _SharedFeature:
    """One shared feature store: rows + indirection + dtype/ratio."""

    def __init__(self, f: Feature):
        self.rows = _share(f._host_full)
        self.id2index = (None if f.id2index is None
                         else _share(np.asarray(f.id2index)))
        self.split_ratio = f.split_ratio
        self.dtype = np.dtype(f.dtype)   # picklable, incl. ml_dtypes

    def arrays(self):
        yield self.rows
        if self.id2index is not None:
            yield self.id2index

    def attach(self, split_ratio: Optional[float]) -> Feature:
        sr = self.split_ratio if split_ratio is None else split_ratio
        return Feature(
            self.rows.array, split_ratio=sr,
            id2index=None if self.id2index is None else self.id2index.array,
            dtype=self.dtype)


def _share_feature_group(nf):
    if nf is None:
        return {}
    group = nf if isinstance(nf, dict) else {None: nf}
    return {k: (None if f is None else _SharedFeature(f))
            for k, f in group.items()}


class DatasetHandle:
    """Picklable description of a shared dataset (a few hundred bytes).

    Members hold :class:`SharedArray` handles; pickling ships segment
    names, not data.  ``indptr`` encodes each graph's node count, so no
    separate size metadata is needed.
    """

    def __init__(self, hetero, topos, node_feats, edge_feats, labels):
        self.hetero = hetero
        self.topos = topos            # key -> (indptr, indices, eids, w)
        self.node_feats = node_feats  # key -> _SharedFeature | None
        self.edge_feats = edge_feats  # key -> _SharedFeature | None
        self.labels = labels          # key -> SharedArray | None
        self._finalizer = None

    def _arrays(self):
        for group in (self.node_feats, self.edge_feats):
            for v in group.values():
                if v is not None:
                    yield from v.arrays()
        for v in self.labels.values():
            if v is not None:
                yield v
        for parts in self.topos.values():
            for v in parts:
                if v is not None:
                    yield v

    def unlink(self) -> None:
        """Free the shm segments (owner side)."""
        for a in self._arrays():
            a.close()
        if self._finalizer is not None:
            atexit.unregister(self.unlink)
            self._finalizer = None


def share_dataset(ds: Dataset) -> DatasetHandle:
    """Copy ``ds``'s host arrays into shared memory once; returns the
    picklable handle.  Segments are freed by ``handle.unlink()`` or at
    process exit."""
    hetero = ds.is_hetero
    graphs = ds.graph if hetero else {None: ds.graph}

    def narrow(arr):
        # Graph.lazy_init consumes int32; sharing int64 would make every
        # worker's astype materialize a private copy of the topology.
        # Narrow once here (values above int32 range would already be
        # unrepresentable in Graph's device arrays).
        arr = np.asarray(arr)
        if (arr.dtype == np.int64
                and (arr.size == 0 or arr.max() < np.iinfo(np.int32).max)):
            return arr.astype(np.int32)
        return arr

    topos = {}
    for k, g in graphs.items():
        t = g.topo
        topos[k] = (_share(narrow(t.indptr)), _share(narrow(t.indices)),
                    _share(narrow(t.edge_ids)), _share(t.edge_weights))

    nl = ds.node_labels
    labels_in = nl if isinstance(nl, dict) else {None: nl}
    labels = {k: _share(v) for k, v in labels_in.items()}

    h = DatasetHandle(hetero, topos,
                      _share_feature_group(ds.node_features),
                      _share_feature_group(ds.edge_features),
                      labels)
    atexit.register(h.unlink)
    h._finalizer = True
    return h


def attach_dataset(handle: DatasetHandle,
                   split_ratio: Optional[float] = 0.0) -> Dataset:
    """Map a shared dataset into this process, zero-copy.

    ``split_ratio`` defaults to 0.0 — sampling workers keep every row in
    the shared host pages (device-resident hot tiers would copy per
    process); pass ``None`` to keep each feature's original ratio.
    """
    def topo(parts):
        indptr, indices, eids, w = parts
        return CSRTopo.from_csr_arrays(
            indptr.array, indices.array,
            None if eids is None else eids.array,
            None if w is None else w.array)

    ds = Dataset()
    # Pin the SharedArray objects (and with them the SharedMemory
    # mappings) to the dataset: the numpy views created below point into
    # those mappings, and SharedMemory unmaps its pages on GC.
    ds._shm_refs = list(handle._arrays())
    if handle.hetero:
        ds.graph = {k: Graph(topo(p), mode="HOST")
                    for k, p in handle.topos.items()}
    else:
        ds.graph = Graph(topo(handle.topos[None]), mode="HOST")

    def group(feats):
        return {k: (None if f is None else f.attach(split_ratio))
                for k, f in feats.items()}

    nfeats = group(handle.node_feats)
    efeats = group(handle.edge_feats)
    if handle.hetero:
        ds.node_features = nfeats or None
        ds.edge_features = efeats or None
        lab = {k: v.array for k, v in handle.labels.items()
               if v is not None}
        # Preserve the original label state: a hetero dataset shared with
        # node_labels=None must attach as None, not {} (the homogeneous
        # branch below already does).
        ds.node_labels = lab or None
    else:
        ds.node_features = nfeats.get(None)
        ds.edge_features = efeats.get(None)
        lab = handle.labels.get(None)
        ds.node_labels = None if lab is None else lab.array
    return ds
