"""CSRTopo — canonical host-side topology container.

Rebuild of the reference's ``graphlearn_torch/python/data/graph.py:28-122``:
accepts COO / CSR / CSC input and canonicalises to CSR, exposing
``indptr / indices / edge_ids / degrees``.  The reference converts through
``torch_sparse.SparseTensor``; here it's plain numpy (host prep only — device
code consumes the finished arrays via :class:`glt_tpu.data.graph.Graph`).
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

from ..utils.topo import coo_to_csr, csr_to_coo, degrees_from_ptr

_LAYOUTS = ("COO", "CSR", "CSC")


class CSRTopo:
    """Graph topology stored as CSR over out-edges.

    Args:
      edge_index: ``[2, E]`` COO (row=src, col=dst) when layout is 'COO',
        otherwise ``(indptr, indices)``.
      edge_ids: optional ``[E]`` global edge ids (default: input positions).
      layout: one of 'COO' | 'CSR' | 'CSC'. 'CSC' is interpreted as the
        CSR of the reverse graph and transposed into out-edge CSR.
      num_nodes: optional override for the node count.
    """

    def __init__(
        self,
        edge_index: Union[np.ndarray, Tuple[np.ndarray, np.ndarray]],
        edge_ids: Optional[np.ndarray] = None,
        layout: str = "COO",
        num_nodes: Optional[int] = None,
        edge_weights: Optional[np.ndarray] = None,
    ):
        layout = layout.upper()
        if layout not in _LAYOUTS:
            raise ValueError(f"layout must be one of {_LAYOUTS}, got {layout!r}")
        if layout == "COO":
            edge_index = np.asarray(edge_index)
            row, col = edge_index[0], edge_index[1]
        else:
            indptr, indices = edge_index
            indptr = np.asarray(indptr)
            row, col = csr_to_coo(indptr, np.asarray(indices))
            if layout == "CSC":
                row, col = col, row
            # The input indptr already encodes the node count (including
            # trailing isolated nodes) — don't let it be re-derived from ids.
            if num_nodes is None:
                num_nodes = indptr.shape[0] - 1
        self._indptr, self._indices, self._edge_ids, perm = coo_to_csr(
            row, col, edge_ids, num_nodes, return_perm=True
        )
        # Per-edge payloads are stored in CSR order, aligned with indices.
        self._edge_weights = (
            None if edge_weights is None else np.asarray(edge_weights)[perm]
        )

    @classmethod
    def from_csr_arrays(
        cls,
        indptr: np.ndarray,
        indices: np.ndarray,
        edge_ids: Optional[np.ndarray] = None,
        edge_weights: Optional[np.ndarray] = None,
    ) -> "CSRTopo":
        """Install finished CSR arrays directly — zero-copy, no COO
        round-trip.  The arrays are adopted as-is (callers guarantee CSR
        validity); used by the shared-memory attach path and benches."""
        t = cls.__new__(cls)
        t._indptr = np.asarray(indptr)
        t._indices = np.asarray(indices)
        t._edge_ids = (np.arange(t._indices.shape[0], dtype=np.int64)
                       if edge_ids is None else np.asarray(edge_ids))
        t._edge_weights = (None if edge_weights is None
                           else np.asarray(edge_weights))
        return t

    @property
    def indptr(self) -> np.ndarray:
        return self._indptr

    @property
    def indices(self) -> np.ndarray:
        return self._indices

    @property
    def edge_ids(self) -> np.ndarray:
        return self._edge_ids

    @property
    def edge_weights(self) -> Optional[np.ndarray]:
        return self._edge_weights

    @property
    def num_nodes(self) -> int:
        return self._indptr.shape[0] - 1

    @property
    def num_edges(self) -> int:
        return int(self._indices.shape[0])

    @property
    def degrees(self) -> np.ndarray:
        return degrees_from_ptr(self._indptr)

    def out_degrees(self) -> np.ndarray:
        return self.degrees

    def in_degrees(self) -> np.ndarray:
        return np.bincount(self._indices, minlength=self.num_nodes)

    def to_coo(self) -> Tuple[np.ndarray, np.ndarray]:
        return csr_to_coo(self._indptr, self._indices)

    def __repr__(self) -> str:
        return f"CSRTopo(num_nodes={self.num_nodes}, num_edges={self.num_edges})"
