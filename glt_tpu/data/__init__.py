from .dataset import Dataset
from .feature import Feature
from .graph import Graph
from .reorder import sort_by_in_degree
from .topology import CSRTopo

__all__ = ["Dataset", "Feature", "Graph", "CSRTopo", "sort_by_in_degree"]
