from .dataset import Dataset
from .feature import Feature
from .feature_cache import (
    FeatureCacheState,
    cache_gather,
    cache_init,
    cache_insert,
    cache_lookup,
    cache_stats,
)
from .graph import Graph
from .reorder import sort_by_in_degree
from .shared import SharedArray, attach_dataset, share_dataset
from .topology import CSRTopo

__all__ = ["Dataset", "Feature", "Graph", "CSRTopo", "SharedArray",
           "attach_dataset", "share_dataset", "sort_by_in_degree",
           "FeatureCacheState", "cache_init", "cache_lookup",
           "cache_insert", "cache_gather", "cache_stats"]
