from .dataset import Dataset
from .feature import Feature
from .graph import Graph
from .reorder import sort_by_in_degree
from .shared import SharedArray, attach_dataset, share_dataset
from .topology import CSRTopo

__all__ = ["Dataset", "Feature", "Graph", "CSRTopo", "SharedArray",
           "attach_dataset", "share_dataset", "sort_by_in_degree"]
