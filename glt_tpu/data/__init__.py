from .graph import Graph
from .topology import CSRTopo

__all__ = ["Graph", "CSRTopo"]
