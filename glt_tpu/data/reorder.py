"""Hotness reordering for the tiered feature store.

Rebuild of the reference's ``sort_by_in_degree`` (python/data/reorder.py:18-40):
feature rows are reordered hottest-first (hotness = in-degree, i.e. how often
a node appears as a sampled neighbor) so that a ``split_ratio`` prefix is the
hot cache.  Returns the ``id2index`` indirection that the feature store
applies on every lookup.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .topology import CSRTopo


def sort_by_in_degree(
    feature: np.ndarray,
    split_ratio: float,
    topo: CSRTopo,
    shuffle_ratio: float = 0.0,
    rng: Optional[np.random.Generator] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Reorder ``feature`` rows by descending in-degree.

    Args:
      feature: ``[N, d]`` row-per-node features.
      split_ratio: fraction of rows that will live in the device (hot) tier —
        only used to scope the optional shuffle.
      topo: topology whose in-degrees define hotness.
      shuffle_ratio: optionally shuffle this fraction of the hot prefix to
        de-bias benchmarks, as the reference supports.

    Returns:
      ``(reordered_feature, id2index)`` where ``id2index[global_id]`` is the
      row of that node in the reordered matrix.
    """
    n = feature.shape[0]
    deg = topo.in_degrees()
    if deg.shape[0] < n:
        deg = np.pad(deg, (0, n - deg.shape[0]))
    order = np.argsort(-deg[:n], kind="stable")  # hottest first
    if shuffle_ratio > 0:
        rng = rng or np.random.default_rng(0)
        limit = int(n * min(split_ratio + shuffle_ratio, 1.0))
        head = order[:limit].copy()
        rng.shuffle(head)
        order = np.concatenate([head, order[limit:]])
    id2index = np.empty(n, np.int32)
    id2index[order] = np.arange(n, dtype=np.int32)
    return np.ascontiguousarray(feature[order]), id2index
