"""Vineyard (GraphScope) store connectors — gated.

Mirrors the reference's optional vineyard integration
(csrc/cpu/vineyard_utils.cc, built only ``WITH_VINEYARD``): reading a
graph's CSR and vertex/edge feature columns out of a vineyard object
store.  The vineyard client libraries are platform infrastructure that is
not part of this environment; the API surface is kept (same three entry
points) and gates on the client being importable, converting straight
into :class:`CSRTopo` / numpy feature blocks when it is.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .topology import CSRTopo


def _require_vineyard():
    try:
        import vineyard  # noqa: F401
        return vineyard
    except ImportError as e:
        raise ImportError(
            "vineyard support requires the 'vineyard' client package "
            "(GraphScope deployments); load your graph via Dataset/"
            "TableDataset.from_arrays instead") from e


def to_csr(sock: str, object_id: int, v_label: int, e_label: int,
           has_eid: bool = True) -> CSRTopo:
    """Read one (v_label, e_label) fragment's CSR (cf. vineyard_utils.cc:32)."""
    vineyard = _require_vineyard()
    client = vineyard.connect(sock)
    frag = client.get(object_id)
    raise NotImplementedError(
        "wire your fragment's indptr/indices arrays into CSRTopo((indptr, "
        "indices), layout='CSR'); the fragment schema is deployment-"
        "specific")


def load_vertex_features(sock: str, object_id: int, v_label: int,
                         columns: Optional[List[str]] = None) -> np.ndarray:
    """cf. vineyard_utils.cc:130 ``LoadVertexFeatures``."""
    _require_vineyard()
    raise NotImplementedError("see to_csr")


def load_edge_features(sock: str, object_id: int, e_label: int,
                       columns: Optional[List[str]] = None) -> np.ndarray:
    """cf. vineyard_utils.cc:189 ``LoadEdgeFeatures``."""
    _require_vineyard()
    raise NotImplementedError("see to_csr")
