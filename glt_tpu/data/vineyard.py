"""Vineyard (GraphScope) store connectors.

Rebuild of the reference's optional vineyard integration
(``csrc/cpu/vineyard_utils.cc``, built only ``WITH_VINEYARD``): reading a
property-graph fragment's CSR topology and vertex/edge feature columns
out of a vineyard object store.

The C++ reference walks an ``ArrowFragment`` — per (v_label, e_label):
the outgoing offset array (vineyard_utils.cc:55), the adjacency list's
neighbor vids + edge ids (:70-90), and Arrow property columns reshaped
into ``[n, k]`` tensors (:100-180).  This module implements the same
three entry points against a small documented **fragment protocol**
(:class:`FragmentProtocol`) so the logic is testable without a vineyard
deployment:

* pass any object implementing the protocol (e.g. :class:`MockFragment`,
  or a thin adapter over your deployment's fragment class), or
* pass ``(sock, object_id)`` to :func:`connect_fragment`, which fetches
  the object through the gated ``vineyard`` client and expects it to
  implement the protocol (GraphScope python fragments can be wrapped in
  a few lines — the schema is deployment-specific, exactly why the
  protocol seam exists).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .topology import CSRTopo


class FragmentProtocol:
    """Duck-typed fragment interface (document, not a base class).

    Mirrors the slices of ``vineyard::ArrowFragment`` the reference
    reads (vineyard_utils.cc:32-247):

    * ``outgoing_offsets(v_label, e_label) -> [n+1] int array`` — CSR
      indptr for the label pair (``GetOutgoingOffsetArray``).
    * ``outgoing_indices(v_label, e_label) -> [E] int array`` — neighbor
      vertex ids (``GetOutgoingAdjList`` neighbors).
    * ``outgoing_edge_ids(v_label, e_label) -> [E] int array or None``
      (``edge_id`` per adjacency entry; None when ``has_eid=False``).
    * ``vertex_columns(v_label) -> Dict[str, np.ndarray]`` — property
      name -> ``[n]`` or ``[n, k]`` column.
    * ``edge_columns(e_label) -> Dict[str, np.ndarray]``.
    """


class MockFragment:
    """In-memory :class:`FragmentProtocol` implementation (tests/dev)."""

    def __init__(self, indptr, indices, edge_ids=None,
                 vertex_cols: Optional[Dict[str, np.ndarray]] = None,
                 edge_cols: Optional[Dict[str, np.ndarray]] = None):
        self._indptr = {(0, 0): np.asarray(indptr)}
        self._indices = {(0, 0): np.asarray(indices)}
        self._eids = {(0, 0): None if edge_ids is None
                      else np.asarray(edge_ids)}
        self._vcols = {0: dict(vertex_cols or {})}
        self._ecols = {0: dict(edge_cols or {})}

    def outgoing_offsets(self, v_label, e_label):
        return self._indptr[(v_label, e_label)]

    def outgoing_indices(self, v_label, e_label):
        return self._indices[(v_label, e_label)]

    def outgoing_edge_ids(self, v_label, e_label):
        return self._eids[(v_label, e_label)]

    def vertex_columns(self, v_label):
        return self._vcols[v_label]

    def edge_columns(self, e_label):
        return self._ecols[e_label]


class ArrowFragmentAdapter:
    """:class:`FragmentProtocol` over a REAL ``vineyard::ArrowFragment``.

    Wraps an object exposing the exact C++ fragment surface the reference
    walks (vineyard_utils.cc:32-189), as bound to Python by
    GraphScope/vineyard deployments:

    * ``GetOutgoingOffsetArray(v_label, e_label)`` +
      ``GetOutgoingOffsetLength(v_label, e_label)`` — the CSR indptr;
    * ``InnerVertices(v_label)`` — iterable of vertex handles;
    * ``GetOutgoingAdjList(v, e_label)`` — iterable of entries with
      ``get_neighbor().GetValue()`` and ``edge_id()``
      (``GetOutgoingRawAdjList`` when edge ids are absent);
    * ``vertex_data_table(v_label)`` / ``edge_data_table(e_label)`` —
      Arrow tables with ``ColumnNames()`` / ``column_names`` and
      ``GetColumnByName(name)`` / ``column(name)`` chunked columns.

    Guarded: needs no vineyard import itself (it only touches the passed
    object), so the adapter — and its tests — run without a deployment;
    ``connect_fragment`` wraps fetched objects in it automatically.
    """

    def __init__(self, frag):
        self._f = frag
        self._adj_cache: Dict[tuple, tuple] = {}

    # -- topology (ToCSR, vineyard_utils.cc:32-96) -----------------------
    def outgoing_offsets(self, v_label, e_label):
        arr = np.asarray(self._f.GetOutgoingOffsetArray(v_label, e_label),
                         dtype=np.int64)
        n = int(self._f.GetOutgoingOffsetLength(v_label, e_label))
        return arr[:n]

    def _walk_adj(self, v_label, e_label):
        """One python pass over the adjacency, cached: ``to_csr`` reads
        both indices and edge ids, and at real fragment scale the
        per-edge python loop dominates load time — never walk twice.
        Entries without ``edge_id`` (fragments loaded without eids) fall
        back to the raw adjacency list, yielding ``eids=None``
        (vineyard_utils.cc:83-92's ``GetOutgoingRawAdjList`` branch).
        """
        key = (v_label, e_label)
        if key in self._adj_cache:
            return self._adj_cache[key]
        nbrs, eids = [], []
        has_eid = True
        try:
            adj = self._f.GetOutgoingAdjList
        except AttributeError:
            # Fragments loaded without edge ids may expose only the raw
            # adjacency surface (vineyard_utils.cc:83-92).
            adj = self._f.GetOutgoingRawAdjList
            has_eid = False
        for v in self._f.InnerVertices(v_label):
            for e in adj(v, e_label):
                nbrs.append(int(e.get_neighbor().GetValue()))
                if has_eid:
                    try:
                        eids.append(int(e.edge_id()))
                    except AttributeError:
                        has_eid = False
        out = (np.asarray(nbrs, dtype=np.int64),
               np.asarray(eids, dtype=np.int64) if has_eid else None)
        self._adj_cache[key] = out
        return out

    def outgoing_indices(self, v_label, e_label):
        return self._walk_adj(v_label, e_label)[0]

    def outgoing_edge_ids(self, v_label, e_label):
        return self._walk_adj(v_label, e_label)[1]

    # -- property columns (LoadVertex/EdgeFeatures, :130-189) ------------
    @staticmethod
    def _chunk_to_numpy(chunk) -> np.ndarray:
        if hasattr(chunk, "to_numpy"):
            try:  # arrow arrays need zero_copy_only=False for strings
                return np.asarray(chunk.to_numpy(zero_copy_only=False))
            except TypeError:
                return np.asarray(chunk.to_numpy())
        return np.asarray(chunk)

    @classmethod
    def _table_columns(cls, tbl) -> Dict[str, np.ndarray]:
        names = (list(tbl.ColumnNames()) if hasattr(tbl, "ColumnNames")
                 else list(tbl.column_names))
        cols = {}
        for name in names:
            col = (tbl.GetColumnByName(name)
                   if hasattr(tbl, "GetColumnByName")
                   else tbl.column(name))
            # Arrow ChunkedArrays hold MULTIPLE chunks at fragment scale
            # (one per record batch) — concatenate them all; a
            # first-chunk-only read silently truncates the table.
            if hasattr(col, "num_chunks"):
                parts = [cls._chunk_to_numpy(col.chunk(i))
                         for i in range(col.num_chunks)]
                cols[name] = (parts[0] if len(parts) == 1
                              else np.concatenate(parts))
            elif hasattr(col, "chunk"):
                cols[name] = cls._chunk_to_numpy(col.chunk(0))
            else:
                cols[name] = cls._chunk_to_numpy(col)
        return cols

    def vertex_columns(self, v_label):
        return self._table_columns(self._f.vertex_data_table(v_label))

    def edge_columns(self, e_label):
        return self._table_columns(self._f.edge_data_table(e_label))


def _require_vineyard():
    try:
        import vineyard  # noqa: F401
        return vineyard
    except ImportError as e:
        raise ImportError(
            "vineyard support requires the 'vineyard' client package "
            "(GraphScope deployments); pass a FragmentProtocol object "
            "directly, or load your graph via Dataset/TableDataset"
        ) from e


def connect_fragment(sock: str, object_id):
    """Fetch a fragment through the vineyard client (gated).

    The returned object must implement :class:`FragmentProtocol`; wrap
    your deployment's fragment class if it does not.
    """
    vineyard = _require_vineyard()
    client = vineyard.connect(sock)
    frag = client.get_object(object_id)
    if all(hasattr(frag, m) for m in ("outgoing_offsets",
                                      "outgoing_indices",
                                      "vertex_columns")):
        return frag
    if all(hasattr(frag, m) for m in ("GetOutgoingOffsetArray",
                                      "InnerVertices",
                                      "vertex_data_table")):
        # A real ArrowFragment binding — adapt it (vineyard_utils.cc's
        # accessor surface).
        return ArrowFragmentAdapter(frag)
    raise TypeError(
        f"vineyard object {object_id} implements neither the fragment "
        f"protocol nor the ArrowFragment accessor surface; wrap it in an "
        f"adapter exposing FragmentProtocol (see module docstring)")


def _resolve(frag_or_sock, object_id):
    if isinstance(frag_or_sock, str):
        return connect_fragment(frag_or_sock, object_id)
    return frag_or_sock


def to_csr(frag_or_sock, object_id=None, v_label: int = 0,
           e_label: int = 0, has_eid: bool = True) -> CSRTopo:
    """Read one (v_label, e_label) fragment CSR into a :class:`CSRTopo`
    (cf. ``ToCSR``, vineyard_utils.cc:32-96).

    Args:
      frag_or_sock: a :class:`FragmentProtocol` object, or a vineyard IPC
        socket path (then ``object_id`` is required).
    """
    frag = _resolve(frag_or_sock, object_id)
    indptr = np.asarray(frag.outgoing_offsets(v_label, e_label),
                        dtype=np.int64)
    indices = np.asarray(frag.outgoing_indices(v_label, e_label),
                         dtype=np.int64)
    tail = int(indptr[-1]) if indptr.ndim == 1 and indptr.size else None
    if tail is None or tail != indices.shape[0]:
        raise ValueError(
            f"fragment CSR is inconsistent: indptr[-1]={tail} but "
            f"{indices.shape[0]} indices")
    edge_ids = None
    if has_eid:
        edge_ids = frag.outgoing_edge_ids(v_label, e_label)
        if edge_ids is not None:
            edge_ids = np.asarray(edge_ids, dtype=np.int64)
    return CSRTopo((indptr, indices), layout="CSR", edge_ids=edge_ids)


def _columns_to_matrix(cols: Dict[str, np.ndarray],
                       selected: Optional[List[str]]) -> np.ndarray:
    """Stack selected property columns into a float32 ``[n, d]`` matrix
    (cf. ``ArrowArray2Tensor`` + the column loop, vineyard_utils.cc:100-180)."""
    names = list(cols.keys()) if selected is None else list(selected)
    if not names:
        raise ValueError("no feature columns selected")
    blocks = []
    n = None
    for name in names:
        if name not in cols:
            raise KeyError(f"fragment has no column {name!r}; available: "
                           f"{sorted(cols)}")
        col = np.asarray(cols[name], dtype=np.float32)
        if col.ndim == 1:
            col = col[:, None]
        if n is None:
            n = col.shape[0]
        elif col.shape[0] != n:
            raise ValueError(
                f"column {name!r} has {col.shape[0]} rows, expected {n}")
        blocks.append(col)
    return np.concatenate(blocks, axis=1)


def load_vertex_features(frag_or_sock, object_id=None, v_label: int = 0,
                         columns: Optional[List[str]] = None) -> np.ndarray:
    """Vertex property columns as ``[n, d]`` float32
    (cf. ``LoadVertexFeatures``, vineyard_utils.cc:130)."""
    frag = _resolve(frag_or_sock, object_id)
    return _columns_to_matrix(frag.vertex_columns(v_label), columns)


def load_edge_features(frag_or_sock, object_id=None, e_label: int = 0,
                       columns: Optional[List[str]] = None) -> np.ndarray:
    """Edge property columns as ``[E, d]`` float32
    (cf. ``LoadEdgeFeatures``, vineyard_utils.cc:189)."""
    frag = _resolve(frag_or_sock, object_id)
    return _columns_to_matrix(frag.edge_columns(e_label), columns)


def fragment_to_dataset(frag, v_label: int = 0, e_label: int = 0,
                        feature_columns: Optional[List[str]] = None,
                        label_column: Optional[str] = None,
                        graph_mode: str = "DEVICE", split_ratio: float = 1.0):
    """Convenience: fragment -> ready-to-sample :class:`Dataset`."""
    from .dataset import Dataset
    from .graph import Graph

    topo = to_csr(frag, v_label=v_label, e_label=e_label)
    ds = Dataset()
    ds.graph = Graph(topo, mode=graph_mode)
    vcols = frag.vertex_columns(v_label)
    feat_cols = feature_columns
    if feat_cols is None:
        feat_cols = [c for c in vcols if c != label_column]
    if feat_cols:
        ds.init_node_features(_columns_to_matrix(vcols, feat_cols),
                              split_ratio=split_ratio)
    if label_column is not None:
        ds.init_node_labels(np.asarray(vcols[label_column]).ravel())
    return ds
