"""Cross-batch device-resident feature-row cache (HBM, static shapes).

The TPU analog of the reference's ``UnifiedTensor`` hotness cache
(csrc/cuda/unified_tensor.cu, python/data/feature.py ``split_ratio``): on
GPU the hot rows live in device memory and the rest is read through UVA;
the cache's job is to keep recently touched rows on the fast side of that
seam.  Here the seam is in front of whatever backing store serves a
``Feature`` — the HBM hot tier (fused in-jit paths) or the host cold tier
(the eager tiered path, where a hit saves a real host->device transfer).

Everything is **functional and jit-safe**: the cache is a
:class:`FeatureCacheState` pytree threaded through the caller (scan
carries, donated jit arguments), updated with pure scatters — no host
sync anywhere.  Replacement is FIFO over a clock hand: misses claim
consecutive slots, evicting the oldest resident (the id->slot map entry
of the evicted id is cleared in the same program).  Hit/miss counters
ride as device scalars and are exported to the bench via
:func:`cache_stats` (one fetch, after the timed region).

Layout (``C`` = capacity, ``N`` = id space, ``d`` = row width):
  * ``table``    ``[C + 1, d]``  cached rows; row ``C`` absorbs masked
    scatter writes (the dump-row trick of ``ops.unique.dense_induce``).
  * ``slot_ids`` ``[C + 1]``     global id resident in each slot (-1 empty).
  * ``id2slot``  ``[N + 2]``     id -> slot (-1 absent); entry ``N`` is the
    padding read slot (never written, always -1), entry ``N + 1`` the
    write dump.
  * ``clock/hits/misses``        int32 device scalars (counters wrap at
    2^31 — fine for bench epochs, not for year-long jobs).
"""
from __future__ import annotations

from typing import Callable, NamedTuple

import jax.numpy as jnp
from jax import lax


class FeatureCacheState(NamedTuple):
    table: jnp.ndarray     # [C + 1, d]
    slot_ids: jnp.ndarray  # [C + 1] int32
    id2slot: jnp.ndarray   # [N + 2] int32
    clock: jnp.ndarray     # [] int32 FIFO hand
    hits: jnp.ndarray      # [] int32 cumulative
    misses: jnp.ndarray    # [] int32 cumulative

    @property
    def capacity(self) -> int:
        return self.slot_ids.shape[0] - 1

    @property
    def dim(self) -> int:
        return self.table.shape[-1]


def cache_init(num_ids: int, capacity: int, dim: int,
               dtype=jnp.float32) -> FeatureCacheState:
    """Empty cache over an id space of ``num_ids`` global ids."""
    if capacity <= 0:
        raise ValueError(f"cache capacity must be positive, got {capacity}")
    from ..obs import device as _device
    _device.register_owner("feature_cache", shape=(capacity + 1, dim),
                           dtype=dtype)
    return FeatureCacheState(
        table=jnp.zeros((capacity + 1, dim), dtype),
        slot_ids=jnp.full((capacity + 1,), -1, jnp.int32),
        id2slot=jnp.full((num_ids + 2,), -1, jnp.int32),
        clock=jnp.zeros((), jnp.int32),
        hits=jnp.zeros((), jnp.int32),
        misses=jnp.zeros((), jnp.int32),
    )


def cache_lookup(state: FeatureCacheState, ids: jnp.ndarray,
                 force: str = "auto"):
    """Probe the cache for ``ids`` (-1 = padding).  jit-safe, read-only.

    Returns ``(rows, hit)``: ``[M, d]`` rows (zeros at misses/padding)
    and the ``[M]`` bool hit mask.  The hit read is itself a random row
    gather over the ``[C, d]`` cache table, so it routes through the
    same autotuned kernel seam as the backing-store gather
    (:func:`~glt_tpu.ops.gather_pallas.gather_rows`, ``force``) — a
    cache that serves most of a batch must not hand the saved HBM
    traffic back as unoptimized table reads.
    """
    from ..ops.gather_pallas import gather_rows

    n = state.id2slot.shape[0] - 2
    valid = ids >= 0
    slot = state.id2slot[jnp.where(valid, jnp.clip(ids, 0, n - 1), n)]
    hit = valid & (slot >= 0)
    c_dump = state.table.shape[0] - 1
    rows = gather_rows(state.table, jnp.where(hit, slot, c_dump),
                       force=force)
    return jnp.where(hit[:, None], rows, 0), hit


def cache_insert(state: FeatureCacheState, ids: jnp.ndarray,
                 rows: jnp.ndarray, want: jnp.ndarray) -> FeatureCacheState:
    """Insert ``rows`` for ``ids`` where ``want`` (FIFO eviction).

    Contract: the wanted ids are unique among themselves and NOT
    currently resident (i.e. ``want`` is a subset of a fresh lookup's
    miss mask) — :func:`cache_gather` guarantees this.  If more ids are
    wanted than the capacity, only the first ``C`` (in position order)
    are inserted.  Counters are untouched (see :func:`cache_gather`).
    """
    cap = state.slot_ids.shape[0] - 1
    n = state.id2slot.shape[0] - 2
    ids = ids.astype(jnp.int32)
    do = want & (ids >= 0)
    rank = jnp.cumsum(do.astype(jnp.int32)) - 1
    do = do & (rank < cap)
    slot = lax.rem(state.clock + rank, cap)
    wslot = jnp.where(do, slot, cap)  # dump slot for masked writes
    # Evict: clear the id->slot entry of each slot's current resident.
    # An evicted id can never equal an inserted id (inserted ids are not
    # resident by contract), so clear-then-set ordering is safe.
    evicted = jnp.where(do, state.slot_ids[wslot], -1)
    id2slot = state.id2slot.at[
        jnp.where(evicted >= 0, evicted, n + 1)].set(-1)
    id2slot = id2slot.at[jnp.where(do, ids, n + 1)].set(
        jnp.where(do, slot, -1))
    slot_ids = state.slot_ids.at[wslot].set(jnp.where(do, ids, -1))
    table = state.table.at[wslot].set(rows.astype(state.table.dtype))
    clock = lax.rem(state.clock + jnp.sum(do.astype(jnp.int32)), cap)
    return state._replace(table=table, slot_ids=slot_ids,
                          id2slot=id2slot, clock=clock)


def cache_gather(state: FeatureCacheState, ids: jnp.ndarray,
                 fetch: Callable[[jnp.ndarray], jnp.ndarray],
                 force: str = "auto"):
    """Serve UNIQUE ``ids`` through the cache; fetch misses via ``fetch``.

    ``fetch(masked_ids) -> [M, d]`` gathers from the backing store with
    the standard padding contract (negative id -> zero row); hits and
    padding arrive pre-masked to -1, so the backing store is only
    touched for true misses.  Returns ``(state', rows)`` with the
    freshly fetched rows inserted and counters bumped.  jit-safe; thread
    ``state`` through your scan carry / donated step arguments.
    ``force`` selects the hit-read gather kernel (see
    :func:`cache_lookup`).

    ``ids`` MUST be duplicate-free among its valid entries (route through
    :func:`~glt_tpu.ops.unique.unique_first_occurrence` first — the dedup
    gather already has) or resident rows may be double-inserted.
    """
    rows_hit, hit = cache_lookup(state, ids, force=force)
    miss = (ids >= 0) & ~hit
    fetched = fetch(jnp.where(miss, ids, -1))
    rows = jnp.where(hit[:, None], rows_hit, fetched.astype(rows_hit.dtype))
    state = cache_insert(state, ids, fetched, miss)
    return state._replace(
        hits=state.hits + jnp.sum(hit.astype(jnp.int32)),
        misses=state.misses + jnp.sum(miss.astype(jnp.int32))), rows


def publish_cache_stats(state: FeatureCacheState,
                        namespace: str = "glt.cache") -> dict:
    """Fetch counters to host and publish them as ``glt.cache.*`` gauges.

    SYNC POINT — call outside timed regions.  This is the canonical read:
    the returned dict is also mirrored into the
    :mod:`glt_tpu.obs.metrics` registry (when metrics are enabled) so
    the cache shows up in one namespace next to loader/remote/server
    counters instead of through ad-hoc dict plumbing.
    """
    import numpy as np

    from ..obs import metrics as _metrics

    h = int(np.asarray(state.hits))
    m = int(np.asarray(state.misses))
    stats = {
        "hits": h,
        "misses": m,
        "lookups": h + m,
        "hit_rate": h / max(h + m, 1),
        "capacity": state.capacity,
        "resident": int(np.asarray(
            jnp.sum((state.slot_ids[:-1] >= 0).astype(jnp.int32)))),
    }
    if _metrics.enabled():
        for k, v in stats.items():
            _metrics.gauge(f"{namespace}.{k}",
                           "feature cache counter (device-scalar fetch)"
                           ).set(v)
    return stats


def cache_stats(state: FeatureCacheState) -> dict:
    """Deprecated alias of :func:`publish_cache_stats`.

    Kept for back-compat; new code should read the cache through the
    unified metrics namespace (``obs.metrics.snapshot()['glt.cache.*']``
    after a :func:`publish_cache_stats` call) rather than plumb this
    dict ad hoc.
    """
    return publish_cache_stats(state)
