"""Dataset — bundle of graph topology, features, and labels (homo & hetero).

Rebuild of the reference's ``Dataset`` (python/data/dataset.py:29-336):
``init_graph / init_node_features / init_edge_features / init_node_labels``
plus hetero accessors (``get_node_types`` etc., dataset.py:238-278).  Hetero
data are dicts keyed by ``NodeType`` / ``EdgeType`` exactly as there.  The
IPC-sharing machinery (ForkingPickler, CUDA IPC) has no TPU role — device
residency is handled by jax Arrays and, across processes, by the loader's
host pipeline.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

import numpy as np

from ..typing import EdgeType, NodeType
from .feature import Feature
from .graph import Graph
from .reorder import sort_by_in_degree
from .topology import CSRTopo

GraphLike = Union[Graph, Dict[EdgeType, Graph]]
FeatureLike = Union[Feature, Dict[Union[NodeType, EdgeType], Feature]]


class Dataset:
    """Graph(s) + node/edge features + labels.

    All init methods accept either a single object (homogeneous) or a dict
    keyed by node/edge type (heterogeneous), mirroring dataset.py:44-219.
    """

    def __init__(
        self,
        graph: Optional[GraphLike] = None,
        node_features: Optional[FeatureLike] = None,
        edge_features: Optional[FeatureLike] = None,
        node_labels: Optional[Union[np.ndarray, Dict[NodeType, np.ndarray]]] = None,
        edge_dir: str = "out",
    ):
        self.graph = graph
        self.node_features = node_features
        self.edge_features = edge_features
        self.node_labels = node_labels
        self.edge_dir = edge_dir

    # -- init methods (cf. dataset.py:44-219) ------------------------------
    def init_graph(
        self,
        edge_index=None,
        edge_ids=None,
        layout: Union[str, Dict[EdgeType, str]] = "COO",
        graph_mode: str = "DEVICE",
        num_nodes=None,
        with_sorted_columns: bool = False,
    ) -> "Dataset":
        if isinstance(edge_index, dict):
            graphs: Dict[EdgeType, Graph] = {}
            for etype, ei in edge_index.items():
                eids = None if edge_ids is None else edge_ids.get(etype)
                lo = layout[etype] if isinstance(layout, dict) else layout
                # CSR rows are the *source* type's nodes (out-edge CSR).
                nn = num_nodes.get(etype[0]) if isinstance(num_nodes, dict) else None
                topo = CSRTopo(ei, edge_ids=eids, layout=lo, num_nodes=nn)
                graphs[etype] = Graph(topo, mode=graph_mode,
                                      with_sorted_columns=with_sorted_columns)
            self.graph = graphs
        elif edge_index is not None:
            topo = CSRTopo(edge_index, edge_ids=edge_ids, layout=layout,
                           num_nodes=num_nodes)
            self.graph = Graph(topo, mode=graph_mode,
                               with_sorted_columns=with_sorted_columns)
        return self

    def init_node_features(
        self,
        node_feature_data=None,
        id2idx=None,
        sort_func=None,
        split_ratio: float = 1.0,
        dtype=None,
    ) -> "Dataset":
        """Build the tiered node feature store.

        ``sort_func`` defaults to in-degree hotness reordering when
        ``split_ratio < 1`` and a homogeneous graph is present (mirroring
        dataset.py's use of ``sort_by_in_degree``).
        """
        if isinstance(node_feature_data, dict):
            feats: Dict[NodeType, Feature] = {}
            for ntype, arr in node_feature_data.items():
                i2i = None if id2idx is None else id2idx.get(ntype)
                feats[ntype] = Feature(arr, split_ratio=split_ratio,
                                       id2index=i2i, dtype=dtype)
            self.node_features = feats
        elif node_feature_data is not None:
            arr, i2i = np.asarray(node_feature_data), id2idx
            if i2i is None and split_ratio < 1.0 and isinstance(self.graph, Graph):
                fn = sort_func or sort_by_in_degree
                arr, i2i = fn(arr, split_ratio, self.graph.topo)
            self.node_features = Feature(arr, split_ratio=split_ratio,
                                         id2index=i2i, dtype=dtype)
        return self

    def init_edge_features(self, edge_feature_data=None, id2idx=None,
                           split_ratio: float = 1.0, dtype=None) -> "Dataset":
        if isinstance(edge_feature_data, dict):
            self.edge_features = {
                etype: Feature(arr, split_ratio=split_ratio,
                               id2index=None if id2idx is None else id2idx.get(etype),
                               dtype=dtype)
                for etype, arr in edge_feature_data.items()}
        elif edge_feature_data is not None:
            self.edge_features = Feature(edge_feature_data,
                                         split_ratio=split_ratio,
                                         id2index=id2idx, dtype=dtype)
        return self

    def init_node_labels(self, node_label_data=None) -> "Dataset":
        if isinstance(node_label_data, dict):
            self.node_labels = {k: np.asarray(v)
                                for k, v in node_label_data.items()}
        elif node_label_data is not None:
            self.node_labels = np.asarray(node_label_data)
        return self

    # -- hetero accessors (cf. dataset.py:238-278) -------------------------
    @property
    def is_hetero(self) -> bool:
        return isinstance(self.graph, dict)

    def get_node_types(self) -> List[NodeType]:
        if not self.is_hetero:
            return []
        types = set()
        for (src, _, dst) in self.graph.keys():
            types.add(src)
            types.add(dst)
        return sorted(types)

    def get_edge_types(self) -> List[EdgeType]:
        if not self.is_hetero:
            return []
        return sorted(self.graph.keys())

    def get_graph(self, etype: Optional[EdgeType] = None) -> Optional[Graph]:
        if isinstance(self.graph, dict):
            return self.graph.get(etype)
        return self.graph

    def get_node_feature(self, ntype: Optional[NodeType] = None):
        if isinstance(self.node_features, dict):
            return self.node_features.get(ntype)
        return self.node_features

    def get_edge_feature(self, etype: Optional[EdgeType] = None):
        if isinstance(self.edge_features, dict):
            return self.edge_features.get(etype)
        return self.edge_features

    def get_node_label(self, ntype: Optional[NodeType] = None):
        if isinstance(self.node_labels, dict):
            return self.node_labels.get(ntype)
        return self.node_labels
