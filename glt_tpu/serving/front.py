"""The serving front: admission control + cross-request coalescer.

Request lifecycle (all timings are ``glt.serving.*`` histograms,
docs/observability.md):

  submit -> [bounded inflight queue] -> coalesce -> micro-batch dispatch
         -> per-request scatter -> complete (or a structured error)

* **Admission** (:meth:`ServingFront.submit`): the inflight queue is
  bounded at ``max_inflight``; a full queue rejects immediately with
  :class:`~glt_tpu.serving.errors.Overloaded` carrying a
  ``retry_after_ms`` hint derived from the measured micro-batch service
  time — a 2x-overloaded server answers every request (mostly with
  "later"), it never grows an unbounded backlog.

* **Coalescing** (:meth:`_collect`): the dispatcher pops the first
  pending request, then holds the micro-batch open up to ``max_wait_ms``
  for co-riders, closing early when ``max_batch_requests`` requests or
  the largest seed bucket fills.  Idle server: one request waits at most
  ``max_wait_ms``.  Loaded server: batches fill instantly and the wait
  never triggers — latency SLO and throughput come from the same knob.

* **Deadline-aware drop**: a request still queued past its deadline is
  completed with ``deadline_exceeded`` at dispatch time — the device
  slot goes to a request someone is still waiting for.

* **Fault containment**: an engine failure fails exactly the requests
  of that micro-batch (structured ``serving_failed``); the dispatcher
  thread survives and the next micro-batch is clean.  A client that
  disconnects mid-coalesce costs its co-riders nothing — completion is
  per-request, delivery failure is the dead connection's alone.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from typing import List, Optional

import numpy as np

from ..obs import flight as _flight
from ..obs import metrics as _metrics
from ..obs import profiler as _profiler
from ..obs.trace import span as _span
from .engine import SubgraphEngine
from .errors import DeadlineExceeded, Overloaded, ServingDown, ServingError
from .options import ServingOptions

# Serving metrics (docs/observability.md "glt.serving.*"): the SLO
# window.  e2e covers submit->complete server-side; the client adds its
# own glt.serving.client_ms around the wire round trip.
_M_REQUESTS = _metrics.counter(
    "glt.serving.requests", "subgraph requests admitted")
_M_OVERLOAD = _metrics.counter(
    "glt.serving.rejected_overload",
    "requests rejected by admission control (queue full)")
_M_DEADLINE = _metrics.counter(
    "glt.serving.rejected_deadline",
    "requests dropped after missing their deadline in queue")
_M_FAILED = _metrics.counter(
    "glt.serving.failed", "requests failed by an engine fault")
_M_BATCHES = _metrics.counter(
    "glt.serving.micro_batches", "coalesced micro-batches dispatched")
_H_QUEUE_WAIT = _metrics.histogram(
    "glt.serving.queue_wait_ms",
    "submit -> coalescer pickup wait per request")
_H_WIDTH = _metrics.histogram(
    "glt.serving.coalesce_width", "requests per dispatched micro-batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
_H_SEEDS = _metrics.histogram(
    "glt.serving.coalesce_seeds", "total seeds per micro-batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
_H_BATCH = _metrics.histogram(
    "glt.serving.batch_ms",
    "micro-batch device stage: sample+gather dispatch + host fetch")
_H_SCATTER = _metrics.histogram(
    "glt.serving.scatter_ms",
    "micro-batch host stage: per-request split/relabel")
_H_E2E = _metrics.histogram(
    "glt.serving.e2e_ms", "submit -> completion per request, server-side")
_M_SHED = _metrics.counter(
    "glt.serving.rejected_shed",
    "requests rejected early while an SLO burn alert sheds load")
_G_SEED_CACHE = _metrics.gauge(
    "glt.serving.seed_cache_hit_rate",
    "hit rate of the replica's seed-affinity LRU (routing quality)")


class _Pending:
    """One inflight request: seeds in, message (or error) out."""

    __slots__ = ("seeds", "deadline", "enqueued", "done", "message",
                 "error")

    def __init__(self, seeds: np.ndarray, deadline: Optional[float]):
        self.seeds = seeds
        self.deadline = deadline          # monotonic, None = no SLO
        self.enqueued = time.monotonic()
        self.done = threading.Event()
        self.message = None
        self.error: Optional[ServingError] = None

    def succeed(self, message) -> None:
        self.message = message
        self.done.set()

    def fail(self, error: ServingError) -> None:
        self.error = error
        self.done.set()


class ServingFront:
    """Admission + coalescing dispatcher over one :class:`SubgraphEngine`.

    Thread-safe for submitters (many connection threads); the engine is
    driven by the single dispatcher thread.
    """

    def __init__(self, dataset, options: ServingOptions,
                 fault_plan=None, engine: Optional[SubgraphEngine] = None):
        self.options = options
        self.engine = engine or SubgraphEngine(dataset, options)
        self._fault_plan = fault_plan
        # The admission bound: submit() never blocks — a full queue is an
        # immediate structured Overloaded, not a hidden stall.
        self._queue: "queue.Queue[_Pending]" = queue.Queue(
            maxsize=int(options.max_inflight))
        self._carry: Optional[_Pending] = None
        self._stop = threading.Event()
        self._stats_lock = threading.Lock()
        self._dispatched_batches = 0
        self._completed = 0
        self._failed = 0
        self._rejected_overload = 0
        self._rejected_deadline = 0
        self._rejected_shed = 0
        # SLO shed-load seam (obs/slo.py): while a burn alert is firing
        # the admission bound shrinks to (1 - shed_frac) of the queue, so
        # the backlog drains instead of feeding the burn.  0.0 = open.
        self._shed_frac = 0.0
        self._shed_slo: Optional[str] = None
        # Seed-affinity LRU: the measured stand-in for "this replica's
        # HBM/DRAM cache has these nodes hot".  Counted per dispatched
        # request (not per admission) so rejected work doesn't pollute
        # the signal; capacity 0 disables it.  Fleet routing quality —
        # affinity vs. hash-random — is read off this hit rate.
        self._seed_cache: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self._seed_cache_cap = int(options.seed_cache_entries)
        self._seed_cache_hits = 0
        self._seed_cache_lookups = 0
        # EWMA of micro-batch service time, seeding the retry-after hint
        # before the first batch lands (compile-heavy) with the wait knob.
        self._ewma_batch_ms = max(10.0, 2.0 * float(options.max_wait_ms))
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="glt-serving-dispatch")
        self._thread.start()

    # -- admission ----------------------------------------------------------
    def submit(self, seeds, deadline_ms: Optional[float] = None) -> _Pending:
        """Validate + admit one request; returns its :class:`_Pending`.

        Raises :class:`BadRequest` / :class:`Overloaded` /
        :class:`ServingDown` instead of queueing doomed work.
        """
        if self._stop.is_set() or not self._thread.is_alive():
            raise ServingDown("serving front is stopped")
        canonical = self.engine.validate_seeds(seeds)
        if deadline_ms is None:
            deadline_ms = self.options.default_deadline_ms
        deadline = (None if deadline_ms is None or deadline_ms <= 0
                    else time.monotonic() + float(deadline_ms) / 1e3)
        pending = _Pending(canonical, deadline)
        shed = self._shed_frac
        if shed > 0.0:
            # Burn alert active: admit only into the un-shed fraction of
            # the queue so the backlog that is burning the SLO drains.
            bound = max(1, int(self._queue.maxsize * (1.0 - shed)))
            if self._queue.qsize() >= bound:
                with self._stats_lock:
                    self._rejected_shed += 1
                _M_SHED.inc()
                _flight.record("serving.rejected_shed",
                               slo=self._shed_slo, shed_frac=shed,
                               inflight=self._queue.qsize())
                raise Overloaded(
                    f"shedding load ({self._shed_slo} SLO burning, "
                    f"shed_frac={shed:g}); retry after the hint",
                    retry_after_ms=self.retry_after_ms()) from None
        try:
            self._queue.put_nowait(pending)
        except queue.Full:
            with self._stats_lock:
                self._rejected_overload += 1
            _M_OVERLOAD.inc()
            _flight.record("serving.rejected_overload",
                           inflight=self._queue.maxsize,
                           retry_after_ms=self.retry_after_ms())
            raise Overloaded(
                f"serving queue full ({self.options.max_inflight} "
                f"inflight); retry after the hint",
                retry_after_ms=self.retry_after_ms()) from None
        _M_REQUESTS.inc()
        return pending

    def slo_alert(self, alert: dict) -> None:
        """``on_alert`` seam for :class:`~glt_tpu.obs.slo.SloMonitor`:
        a firing burn alert shrinks admission by the alert's
        ``shed_frac``; the resolve transition re-opens it.  Safe from
        the monitor's sampling thread (single attribute writes)."""
        if alert.get("state") == "firing":
            self._shed_frac = float(alert.get("shed_frac") or 0.5)
            self._shed_slo = alert.get("slo")
            _flight.record("serving.shed_on", slo=self._shed_slo,
                           shed_frac=self._shed_frac)
            # One bounded profiler capture per firing (rate-limited
            # inside the profiler; no-op unless armed): the trace of
            # the incident, taken while it is happening.
            prof = _profiler.armed()
            if prof is not None:
                prof.trigger("slo:" + str(self._shed_slo))
        else:
            _flight.record("serving.shed_off", slo=alert.get("slo"))
            self._shed_frac = 0.0
            self._shed_slo = None

    def retry_after_ms(self) -> float:
        """Backoff hint: how long until a queue slot should open —
        the queue's depth in micro-batches times the measured batch
        service time."""
        depth_batches = 1 + (self._queue.qsize()
                             // max(1, self.options.max_batch_requests))
        return round(depth_batches * self._ewma_batch_ms, 3)

    def wait_budget_s(self, deadline_ms: Optional[float]) -> float:
        """Server-side wait bound for a connection thread blocked on a
        pending result: the request's deadline budget plus one queue's
        worth of service time (compile of a cold bucket rides inside —
        the deadline clock, not this bound, is what drops it)."""
        budget = (self.options.default_deadline_ms
                  if deadline_ms is None else float(deadline_ms))
        slack = (self._queue.maxsize + 1) * self._ewma_batch_ms + 1000.0
        return (max(budget, 0.0) + slack) / 1e3

    # -- coalescer ----------------------------------------------------------
    def _collect(self) -> List[_Pending]:
        """Pop one micro-batch: first pending request (bounded poll so
        stop is observed), then co-riders until width/seed/wait limits."""
        first = self._carry
        self._carry = None
        while first is None:
            if self._stop.is_set():
                return []
            try:
                first = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
        batch = [first]
        total = first.seeds.size
        max_bucket = self.engine.buckets[-1]
        close_at = time.monotonic() + float(self.options.max_wait_ms) / 1e3
        while len(batch) < self.options.max_batch_requests:
            rem = close_at - time.monotonic()
            if rem <= 0:
                break
            try:
                nxt = self._queue.get(timeout=rem)
            except queue.Empty:
                break
            if total + nxt.seeds.size > max_bucket:
                # Does not fit this bucket: lead the next micro-batch.
                self._carry = nxt
                break
            batch.append(nxt)
            total += nxt.seeds.size
        return batch

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self._collect()
            if batch:
                self._dispatch(batch)
        # Drain on stop: everything still queued fails structurally.
        leftovers, self._carry = [self._carry], None
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for p in leftovers:
            if p is not None and not p.done.is_set():
                p.fail(ServingDown("serving front stopped"))

    def _dispatch(self, batch: List[_Pending]) -> None:
        now = time.monotonic()
        live: List[_Pending] = []
        for p in batch:
            _H_QUEUE_WAIT.observe((now - p.enqueued) * 1e3)
            if p.deadline is not None and now > p.deadline:
                with self._stats_lock:
                    self._rejected_deadline += 1
                _M_DEADLINE.inc()
                p.fail(DeadlineExceeded(
                    f"request spent {(now - p.enqueued) * 1e3:.1f} ms "
                    f"queued, past its deadline; dropped undispatched"))
                continue
            live.append(p)
        if not live:
            return
        if self._seed_cache_cap > 0:
            self._touch_seed_cache(live)
        _H_WIDTH.observe(len(live))
        _H_SEEDS.observe(sum(p.seeds.size for p in live))
        t0 = time.perf_counter()
        try:
            if self._fault_plan is not None:
                self._fault_plan.on_serving_batch()
            with _span("serving.micro_batch", width=len(live)):
                with _H_BATCH.time():
                    coal = self.engine.sample([p.seeds for p in live])
                with _H_SCATTER.time():
                    messages = self.engine.scatter(coal)
        except Exception as e:  # noqa: BLE001 — relayed per request
            # Engine fault: fail exactly this micro-batch's requests with
            # a structured error; the dispatcher (and every later
            # micro-batch) keeps serving.
            with self._stats_lock:
                self._failed += len(live)
            _M_FAILED.inc(len(live))
            for p in live:
                p.fail(ServingError(f"serving engine failed: {e}"))
            return
        batch_ms = (time.perf_counter() - t0) * 1e3
        done = time.monotonic()
        for p, msg in zip(live, messages):
            p.succeed(msg)
            _H_E2E.observe((done - p.enqueued) * 1e3)
        with self._stats_lock:
            # The EWMA update is a read-modify-write: it must share the
            # stats lock that `stats()` reads it under (found by gltlint
            # GLT027 — the unlocked `+=` could publish a torn/stale
            # estimate into retry_after_ms hints under contention).
            self._ewma_batch_ms += 0.2 * (batch_ms - self._ewma_batch_ms)
            self._dispatched_batches += 1
            self._completed += len(live)
        _M_BATCHES.inc()

    def _touch_seed_cache(self, live: List[_Pending]) -> None:
        """Count every dispatched seed against the affinity LRU.

        Only the dispatcher thread mutates the dict; the stats lock
        covers the counters so :meth:`stats` reads a consistent pair.
        """
        cache, cap = self._seed_cache, self._seed_cache_cap
        hits = lookups = 0
        for p in live:
            for s in p.seeds.tolist():
                lookups += 1
                if s in cache:
                    hits += 1
                    cache.move_to_end(s)
                else:
                    cache[s] = None
                    if len(cache) > cap:
                        cache.popitem(last=False)
        with self._stats_lock:
            self._seed_cache_hits += hits
            self._seed_cache_lookups += lookups
            hit_rate = (self._seed_cache_hits
                        / max(1, self._seed_cache_lookups))
        _G_SEED_CACHE.set(round(hit_rate, 6))

    # -- introspection / lifecycle ------------------------------------------
    def stats(self) -> dict:
        """JSON-able occupancy/outcome counters (the ``serving_stats``
        wire op; the bench reads rejection counts from here)."""
        with self._stats_lock:
            return {
                "inflight": self._queue.qsize(),
                "max_inflight": self._queue.maxsize,
                "dispatched_batches": self._dispatched_batches,
                "completed": self._completed,
                "failed": self._failed,
                "rejected_overload": self._rejected_overload,
                "rejected_deadline": self._rejected_deadline,
                "rejected_shed": self._rejected_shed,
                "shed_frac": self._shed_frac,
                "shed_slo": self._shed_slo,
                "ewma_batch_ms": round(self._ewma_batch_ms, 3),
                "compiled_buckets": self.engine.compiled_buckets(),
                "seed_cache_hits": self._seed_cache_hits,
                "seed_cache_lookups": self._seed_cache_lookups,
                "seed_cache_hit_rate": round(
                    self._seed_cache_hits
                    / max(1, self._seed_cache_lookups), 6),
            }

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)
