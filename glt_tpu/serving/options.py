"""Serving configuration: coalescing policy + admission bounds.

One dataclass so ``init_server(serving=ServingOptions(...))`` carries the
whole policy: which sampling program shapes exist (``seed_buckets`` —
the static-shape buckets that keep XLA from recompiling per request
width), how long an idle server waits to coalesce
(``max_wait_ms`` — the latency/throughput dial), and how much inflight
work admission control admits before rejecting with ``Overloaded``
(``max_inflight`` — the bounded queue that keeps a 2x-overload server
answering instead of growing without bound).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass
class ServingOptions:
    """Policy knobs for the :mod:`glt_tpu.serving` front on a server.

    Attributes:
      num_neighbors: per-hop fanouts of the shared serving sampler (the
        same shape every coalesced micro-batch runs).
      seed_buckets: ascending padded seed-vector widths; a micro-batch
        is padded to the smallest bucket holding its total seed count,
        so the device sees one compiled program per bucket instead of
        one per request mix.  The largest bucket bounds how many seeds
        one dispatch can coalesce.
      max_seeds_per_request: per-request seed-set bound (the 1-100-node
        ego-subgraph contract); larger requests are rejected
        ``bad_request`` — split them client-side.
      max_batch_requests: at most this many requests share one
        micro-batch (1 = per-request dispatch, the bench baseline).
      max_wait_ms: how long the coalescer holds a non-full micro-batch
        open for co-riders.  An idle server pays at most this much
        extra latency; a loaded one never waits (the batch fills
        first).
      max_inflight: bound on queued-but-undispatched requests;
        admission control rejects past it with a structured
        ``Overloaded`` + ``retry_after_ms`` hint.
      default_deadline_ms: per-request SLO budget when the client sends
        none; a request still queued past its deadline is dropped with
        ``deadline_exceeded`` instead of wasting a device slot.
      with_features / with_labels: gather node features/labels into the
        response (one shared gather per micro-batch — the cross-request
        I/O coalescing win).
      with_edge: include global edge ids in responses.
      frontier_cap: optional per-hop frontier cap forwarded to the
        sampler (memory knob for wide fanouts).
      seed_cache_entries: capacity of the replica's seed-affinity LRU —
        the stand-in for "this replica's HBM/DRAM cache has this node's
        rows hot".  Every dispatched request counts its seeds against
        the LRU (``seed_cache_hit_rate`` in ``stats()``), which is what
        makes cache affinity a *measured* property of fleet routing:
        partition-affinity routing keeps each replica's LRU on a stable
        shard of the id space, hash-random routing churns it.  0
        disables the bookkeeping.
      seed: base RNG seed for the serving samplers.
    """

    num_neighbors: Sequence[int] = (10, 5)
    seed_buckets: Tuple[int, ...] = (8, 32, 128)
    max_seeds_per_request: int = 100
    max_batch_requests: int = 32
    max_wait_ms: float = 2.0
    max_inflight: int = 64
    default_deadline_ms: float = 1000.0
    with_features: bool = True
    with_labels: bool = True
    with_edge: bool = True
    frontier_cap: Optional[int] = None
    seed_cache_entries: int = 4096
    seed: int = 0

    def __post_init__(self):
        buckets = tuple(sorted(int(b) for b in self.seed_buckets))
        if not buckets or any(b <= 0 for b in buckets):
            raise ValueError(f"seed_buckets must be positive, got "
                             f"{self.seed_buckets!r}")
        self.seed_buckets = buckets
        if int(self.max_seeds_per_request) > buckets[-1]:
            raise ValueError(
                f"max_seeds_per_request {self.max_seeds_per_request} "
                f"exceeds the largest seed bucket {buckets[-1]}: a "
                f"single admissible request must fit one micro-batch")
        if int(self.max_batch_requests) < 1:
            raise ValueError("max_batch_requests must be >= 1")
        if int(self.max_inflight) < 1:
            raise ValueError("max_inflight must be >= 1")
        if int(self.seed_cache_entries) < 0:
            raise ValueError("seed_cache_entries must be >= 0")
