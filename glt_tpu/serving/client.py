"""InferenceClient: the thin latency-path client for subgraph serving.

A :class:`~glt_tpu.distributed.dist_client.RemoteServerConnection`
underneath (same framed protocol, reconnect/backoff/failover machinery),
driven with serving-appropriate knobs: every ``subgraph`` round trip
carries a **per-op socket timeout** derived from the request's deadline
(the PR-9 per-op timeout seam — training fetches keep their generous
``rpc_timeout``, serving ops fail fast), and structured server rejections
surface as typed :mod:`glt_tpu.serving.errors` exceptions —
``Overloaded`` with its ``retry_after_ms`` hint, ``DeadlineExceeded``,
``BadRequest`` — never as retry loops hidden inside the client.
"""
from __future__ import annotations

import json
from typing import Optional, Sequence, Tuple

import numpy as np

from ..channel.serialization import deserialize
from ..distributed.dist_client import RemoteServerConnection
from ..distributed.dist_server import _KIND_JSON, _KIND_SUB
from ..distributed.sample_message import message_to_batch
from ..obs import metrics as _metrics
from ..obs import propagate as _prop
from ..obs.trace import span as _span
from .errors import DeadlineExceeded, ServingError

_H_CLIENT = _metrics.histogram(
    "glt.serving.client_ms",
    "client-observed subgraph round trip (serialize+wire+serve)")


def retryable_transport(exc: BaseException) -> bool:
    """True for transport-class failures a retry can plausibly fix —
    ECONNRESET, socket timeouts, EOF mid-frame, desynced framing — as
    opposed to structured serving rejections (the server speaking
    clearly) which must surface to the caller's policy untouched.

    ``RemoteServerConnection._exchange`` wraps its final transport
    failure in a ``RuntimeError`` chained ``from`` the last retryable
    exception, so the cause is inspected too (the fleet router and
    ``subgraph_with_retry`` both classify through here).
    """
    if isinstance(exc, ServingError):
        return False
    if isinstance(exc, RemoteServerConnection.RETRYABLE):
        return True
    if isinstance(exc, RuntimeError):
        return isinstance(exc.__cause__, RemoteServerConnection.RETRYABLE)
    return False


class InferenceClient:
    """Request ego-subgraphs from a serving-enabled ``DistServer``.

    Args:
      addr: server ``(host, port)``.
      timeout: default per-request deadline budget, SECONDS — sent to
        the server as ``deadline_ms`` (its drop-if-late SLO) and used to
        derive the per-op socket timeout.
      op_timeout_margin: added to the deadline for the socket timeout,
        covering serialization + scheduling slack (and, on the very
        first request per bucket, server-side compilation — raise it or
        pre-warm via ``ServingOptions``/a throwaway request if cold
        compiles exceed it).
      max_retries: transport-level retries per exchange (reconnect +
        resend).  Serving requests are stateless/idempotent server-side,
        so a retried request at worst costs a wasted micro-batch slot.
        Structured rejections (Overloaded etc.) are never retried here —
        backoff policy belongs to the caller.
      to_device: reconstruct batches as device arrays (training-style)
        or host numpy (the default for serving consumers).
    """

    def __init__(self, addr: Tuple[int, int], timeout: float = 1.0,
                 op_timeout_margin: float = 30.0,
                 max_retries: int = 1,
                 fallback_addrs: Sequence[Tuple[str, int]] = (),
                 backoff_base: float = 0.05, backoff_cap: float = 2.0,
                 fault_plan=None, seed: int = 0,
                 to_device: bool = False):
        self.default_timeout = float(timeout)
        self.op_timeout_margin = float(op_timeout_margin)
        self.to_device = bool(to_device)
        self._retries = int(max_retries)
        self.conn = RemoteServerConnection(
            addr, max_retries=max_retries,
            backoff_base=backoff_base, backoff_cap=backoff_cap,
            fallback_addrs=tuple(fallback_addrs),
            fault_plan=fault_plan, seed=seed)

    def subgraph(self, seeds, timeout: Optional[float] = None):
        """One ego-subgraph request; returns a
        :class:`~glt_tpu.loader.transform.Batch` whose first
        ``batch_size`` node slots are the (deduplicated) seeds.

        Raises the typed serving errors on structured rejection and the
        usual transport errors past the retry budget.
        """
        t = self.default_timeout if timeout is None else float(timeout)
        req = {
            "op": "subgraph_request",
            "seeds": np.asarray(seeds).astype(np.int64).ravel().tolist(),
            "deadline_ms": t * 1e3,
        }
        with _span("serving.client_request",
                   seeds=len(req["seeds"])) as sp, _H_CLIENT.time():
            _prop.inject(req, sp)
            kind, data, t0, t3 = self.conn._exchange(
                json.dumps(req).encode(), retries=self._retries,
                timeout=t + self.op_timeout_margin)
            if kind == _KIND_JSON:
                resp = json.loads(data)
                if "error" in resp:
                    self.conn._raise_structured(resp)
                raise RuntimeError(
                    f"expected a subgraph frame, got JSON {resp!r}")
            if kind != _KIND_SUB:
                raise RuntimeError(f"unexpected frame kind {kind}")
            if _prop.WIRE_KEY in req:
                payload, echo = _prop.split_trailer(data)
                _prop.record_clock_sync(echo, t0, t3)
            else:
                payload = memoryview(data)
            msg = deserialize(payload)
        return message_to_batch(msg, to_device=self.to_device)

    def subgraph_with_retry(self, seeds, timeout: Optional[float] = None,
                            attempts: int = 3,
                            max_backoff_s: float = 0.5,
                            deadline_ms: Optional[float] = None):
        """``subgraph`` plus a bounded, budgeted retry loop.

        Retries two failure classes, each with its own backoff policy:

        * structured ``Overloaded`` — honor the server's
          ``retry_after_ms`` hint (capped at ``max_backoff_s``);
        * retryable transport errors (ECONNRESET, socket timeout, EOF
          mid-frame — :func:`retryable_transport`) — the connection's
          own exponential backoff with seeded jitter
          (``backoff_base``/``backoff_cap``, the PR-4 parameters).

        Any other serving error propagates immediately.  ``deadline_ms``
        caps the TOTAL retry budget across every attempt and sleep (not
        per-attempt): once elapsed time exceeds it the loop raises
        :class:`~glt_tpu.serving.errors.DeadlineExceeded` chained from
        the last failure, and each attempt's per-request timeout is
        clipped to the remaining budget so a slow server cannot eat the
        whole budget in one socket wait.
        """
        import time as _time

        start = _time.monotonic()
        budget_s = None if deadline_ms is None else float(deadline_ms) / 1e3

        def remaining() -> Optional[float]:
            if budget_s is None:
                return None
            return budget_s - (_time.monotonic() - start)

        last: Optional[BaseException] = None
        for attempt in range(max(1, int(attempts))):
            rem = remaining()
            if rem is not None and rem <= 0:
                raise DeadlineExceeded(
                    f"retry budget of {deadline_ms:.0f} ms exhausted "
                    f"after {attempt} attempt(s)") from last
            t = self.default_timeout if timeout is None else float(timeout)
            if rem is not None:
                t = min(t, rem)
            try:
                return self.subgraph(seeds, timeout=t)
            except ServingError as e:
                if e.code != "overloaded":
                    raise
                last = e
                hint = (e.retry_after_ms or 10.0) / 1e3
                sleep_s = min(max_backoff_s, hint)
            except Exception as e:  # noqa: BLE001 — reclassified below
                if not retryable_transport(e):
                    raise
                last = e
                # The connection's own jittered exponential backoff
                # (seeded rng: reproducible, decorrelated across clients).
                sleep_s = min(self.conn.backoff_cap,
                              self.conn.backoff_base * (2 ** attempt))
                sleep_s *= 0.5 + 0.5 * self.conn._rng.random()
            rem = remaining()
            if rem is not None:
                sleep_s = min(sleep_s, max(0.0, rem))
            _time.sleep(sleep_s)
        raise last

    def stats(self) -> dict:
        """The server's ``serving_stats`` table (queue depth, rejection
        counters, compiled buckets)."""
        return self.conn.request(op="serving_stats")

    def close(self) -> None:
        self.conn.close()
