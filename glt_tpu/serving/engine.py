"""Coalesced ego-subgraph engine: one device program serves N requests.

The device side is exactly the training sampler (one fused multi-hop
:class:`~glt_tpu.sampler.neighbor_sampler.NeighborSampler` program) plus
one shared feature gather — the PR-2/3 primitives the ROADMAP said were
"waiting to be driven by a request scheduler".  What this module adds is
the request-level plumbing around them:

* **Buckets, not shapes-per-request.**  All outstanding requests' seeds
  are concatenated into one -1-padded seed vector, padded up to the
  smallest configured bucket that holds it.  Each bucket compiles once
  (lazily); afterwards every micro-batch reuses a cached executable —
  no per-request recompiles, the GLT003 hazard serving cannot afford.

* **Shared dedup.**  Seeds and frontiers dedup ACROSS requests inside
  the one program: a node two clients both reach is sampled once and
  its feature row is gathered once.  This is the cross-request data-I/O
  coalescing BGL measures as the serving win.

* **Per-request scatter.**  The merged sample is split back per request
  on the host: a depth-limited BFS over the sampled COO from each
  request's seed slots selects exactly the edges within ``num_hops`` of
  its seeds, nodes are relabeled request-locally (seeds first, loader
  contract), and each client receives a standard
  :data:`~glt_tpu.channel.base.SampleMessage` — ``message_to_batch``
  reconstructs a :class:`~glt_tpu.loader.transform.Batch` unchanged.

Sharing semantics: a request's subgraph is its seeds' ``num_hops``-ball
*within the merged sample*.  Where neighborhoods overlap, requests see
the same sampled edges (one draw, shared); where they don't, results
are independent — the isolation the multi-client tests assert.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..channel.base import SampleMessage
from ..obs import compilewatch as _compilewatch
from ..obs import device as _device
from ..sampler.base import NodeSamplerInput
from ..sampler.neighbor_sampler import NeighborSampler
from ..typing import PADDING_ID
from .errors import BadRequest
from .options import ServingOptions

_META_BS = "#META.batch_size"


class CoalescedSample:
    """Host-side view of one dispatched micro-batch (sample + gather
    fetched in a single device->host sync) plus the seed-slot
    bookkeeping :meth:`SubgraphEngine.scatter` splits results back by."""

    __slots__ = ("seed_lists", "bucket", "node", "row", "col", "edge",
                 "edge_mask", "x", "y", "num_hops")

    def __init__(self, seed_lists, bucket, node, row, col, edge,
                 edge_mask, x, y, num_hops):
        self.seed_lists = seed_lists
        self.bucket = bucket
        self.node = node
        self.row = row
        self.col = col
        self.edge = edge
        self.edge_mask = edge_mask
        self.x = x
        self.y = y
        self.num_hops = num_hops


class SubgraphEngine:
    """Bucketed sample->dedup->gather programs + per-request splitting.

    Thread-compatible, not thread-hot: the serving front drives it from
    ONE dispatcher thread; the lock only guards lazy sampler
    construction (stats readers race it harmlessly).
    """

    def __init__(self, dataset, options: ServingOptions):
        self.dataset = dataset
        self.options = options
        self.graph = dataset.get_graph()
        self.num_nodes = int(self.graph.num_nodes)
        self.num_neighbors = list(options.num_neighbors)
        self.buckets = tuple(options.seed_buckets)
        self._feature = (dataset.get_node_feature()
                        if options.with_features else None)
        labels = (dataset.get_node_label()
                  if options.with_labels else None)
        self._labels = None if labels is None else np.asarray(labels)
        self._samplers: Dict[int, NeighborSampler] = {}
        self._owner_registered: set = set()
        self._lock = threading.Lock()

    # -- request validation -------------------------------------------------
    def validate_seeds(self, seeds) -> np.ndarray:
        """Canonicalize one request's seed set (dedup, order-preserving).

        Raises :class:`BadRequest` on an empty/oversized set or ids
        outside the graph — the non-retryable failure class.
        """
        arr = np.asarray(seeds)
        if arr.ndim != 1 or arr.size == 0:
            raise BadRequest(
                f"seed set must be a non-empty 1-D id list, got shape "
                f"{arr.shape}")
        if not np.issubdtype(arr.dtype, np.integer):
            raise BadRequest(f"seed ids must be integers, got {arr.dtype}")
        arr = arr.astype(np.int64)
        if arr.min() < 0 or arr.max() >= self.num_nodes:
            raise BadRequest(
                f"seed ids must lie in [0, {self.num_nodes}), got range "
                f"[{arr.min()}, {arr.max()}]")
        # Order-preserving dedup: the response's seed block mirrors the
        # request's first-occurrence order.
        _, first = np.unique(arr, return_index=True)
        arr = arr[np.sort(first)]
        if arr.size > self.options.max_seeds_per_request:
            raise BadRequest(
                f"{arr.size} distinct seeds exceeds the per-request bound "
                f"{self.options.max_seeds_per_request}; split the request")
        return arr.astype(np.int32)

    def bucket_for(self, total_seeds: int) -> int:
        for b in self.buckets:
            if total_seeds <= b:
                return b
        raise BadRequest(
            f"{total_seeds} coalesced seeds exceed the largest bucket "
            f"{self.buckets[-1]}")

    def _sampler(self, bucket: int) -> NeighborSampler:
        with self._lock:
            s = self._samplers.get(bucket)
            if s is None:
                s = NeighborSampler(
                    self.graph, self.num_neighbors, batch_size=bucket,
                    frontier_cap=self.options.frontier_cap,
                    with_edge=self.options.with_edge,
                    seed=self.options.seed + bucket)
                self._samplers[bucket] = s
            return s

    def compiled_buckets(self) -> List[int]:
        with self._lock:
            return sorted(self._samplers)

    def warmup(self) -> None:
        """Compile every bucket's program up front (optional; the first
        real request per bucket otherwise pays the compile)."""
        for b in self.buckets:
            self.sample([np.zeros((1,), np.int32)], bucket=b)

    # -- device stage -------------------------------------------------------
    def sample(self, seed_lists: Sequence[np.ndarray],
               bucket: Optional[int] = None) -> CoalescedSample:
        """Run one coalesced micro-batch through the shared program.

        ``seed_lists``: per-request canonical seed arrays (see
        :meth:`validate_seeds`).  Returns the host-fetched merged sample
        — ONE device dispatch and ONE device->host sync for the whole
        micro-batch, regardless of how many requests ride it.
        """
        import jax

        total = int(sum(s.size for s in seed_lists))
        if bucket is None:
            bucket = self.bucket_for(total)
        seeds = np.full((bucket,), PADDING_ID, np.int32)
        off = 0
        for s in seed_lists:
            seeds[off: off + s.size] = s
            off += s.size
        sampler = self._sampler(bucket)
        # Each bucket compiles once; any further compilation under this
        # label is bucket churn — the storm compilewatch exists to catch.
        with _compilewatch.label(f"serving_bucket_{bucket}"):
            out = sampler.sample_from_nodes(NodeSamplerInput(seeds))
            x = None
            if self._feature is not None:
                x = self._feature.gather(out.node)
        if bucket not in self._owner_registered:
            # First micro-batch per bucket: claim the sample-buffer
            # fingerprints so the device census attributes them to us.
            for arr in (out.node, out.row, out.col):
                _device.register_owner("serving", array=arr)
            self._owner_registered.add(bucket)
        node, row, col, edge, edge_mask, x_h = jax.device_get(
            (out.node, out.row, out.col, out.edge, out.edge_mask, x))
        y = None
        if self._labels is not None:
            safe = np.clip(node, 0, self._labels.shape[0] - 1)
            y = np.where(node >= 0, self._labels[safe],
                         PADDING_ID).astype(np.int32)
        return CoalescedSample(
            seed_lists=list(seed_lists), bucket=bucket,
            node=np.asarray(node), row=np.asarray(row),
            col=np.asarray(col),
            edge=None if edge is None else np.asarray(edge),
            edge_mask=np.asarray(edge_mask),
            x=None if x_h is None else np.asarray(x_h), y=y,
            num_hops=len(self.num_neighbors))

    # -- host scatter stage -------------------------------------------------
    def scatter(self, coal: CoalescedSample) -> List[SampleMessage]:
        """Scatter the merged sample back into per-request messages.

        Per request: a ``num_hops``-bounded BFS over the sampled COO
        from its seed slots (membership over node-buffer locals, so
        shared nodes cost nothing extra), then request-local relabeling
        with the request's seeds occupying the first slots (the loader
        ``Batch`` contract).
        """
        node, row, col = coal.node, coal.row, coal.col
        cap = node.shape[0]
        bucket = coal.bucket
        # Unique seeds land in the first `bucket` node-buffer slots
        # (first-occurrence order); map id -> local once per micro-batch.
        pos: Dict[int, int] = {}
        for i in range(bucket):
            v = int(node[i])
            if v >= 0 and v not in pos:
                pos[v] = i
        valid = coal.edge_mask & (row >= 0) & (col >= 0)
        row_c = np.where(valid, row, 0)
        col_c = np.where(valid, col, 0)
        out: List[SampleMessage] = []
        for seeds in coal.seed_lists:
            member = np.zeros((cap,), bool)
            seed_locs = np.asarray([pos[int(s)] for s in seeds], np.int64)
            member[seed_locs] = True
            frontier = member.copy()
            sel = np.zeros(valid.shape, bool)
            for _ in range(coal.num_hops):
                new_e = valid & frontier[col_c] & ~sel
                if not new_e.any():
                    break
                sel |= new_e
                reached = np.zeros((cap,), bool)
                reached[row_c[new_e]] = True
                frontier = reached & ~member
                member |= reached
            rest = member.copy()
            rest[seed_locs] = False
            order = np.concatenate([seed_locs, np.flatnonzero(rest)])
            local = np.full((cap,), PADDING_ID, np.int32)
            local[order] = np.arange(order.size, dtype=np.int32)
            n = order.size
            e_idx = np.flatnonzero(sel)
            msg: SampleMessage = {
                "node": node[order].astype(np.int32),
                "row": local[row_c[e_idx]],
                "col": local[col_c[e_idx]],
                "node_mask": np.ones((n,), bool),
                "edge_mask": np.ones((e_idx.size,), bool),
                "batch": np.asarray(seeds, np.int32),
                _META_BS: np.array(seeds.size, np.int64),
            }
            if coal.edge is not None:
                msg["edge"] = coal.edge[e_idx].astype(np.int32)
            if coal.x is not None:
                msg["x"] = coal.x[order]
            if coal.y is not None:
                msg["y"] = coal.y[order]
            out.append(msg)
        return out
