"""FleetController: one SLO evaluation driving a whole serving fleet.

PR 12 gave a single replica reflexes — a local
:class:`~glt_tpu.obs.slo.SloMonitor` shedding its own admission bound.
A fleet must not shed replica-by-replica (the burn migrates to whichever
replica still admits); the controller here evaluates ONE
:class:`~glt_tpu.obs.slo.SloSpec` set against **fleet-aggregated**
instruments and broadcasts the firing/resolved transitions to every
replica over the ``fleet_shed`` wire op, so the whole fleet opens and
closes admission together.

Mechanics per :meth:`FleetController.tick` (public and deterministic —
tests and CI drive it with an injected ``now``):

1. Pull every replica's ``serving_stats`` + ``fleet_health``; a
   successful pull beats that replica in the controller's supervisor.
2. Mirror the fleet aggregates into local ``glt.fleet.*`` instruments
   (cumulative counters for admitted/rejected, gauges for latency and
   survivor cache hit rate) — the SloMonitor then evaluates them with
   the exact windowed burn-rate math a single replica uses.
3. ``SloMonitor.tick(now)``: state transitions broadcast via
   ``fleet_shed`` (legacy replicas tolerate the op failing — they
   degrade to their own local policy).

On any replica death (its supervisor deadline expires, or the router
reports a transport-level kill) the controller writes the **merged
postmortem**: every surviving replica's ``flight_dump`` plus its own
ring, merged by :func:`glt_tpu.obs.flight.merge_flight_dumps` into one
file an operator reconstructs the incident from — which replica died,
when its shards re-homed, and the shed window around it
(``python -m glt_tpu.obs merge`` produces the same artifact by hand).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..distributed.dist_client import RemoteServerConnection
from ..distributed.supervisor import Supervisor
from ..obs import flight as _flight
from ..obs import metrics as _metrics
from ..obs.slo import DEFAULT_WINDOWS, SloMonitor, SloSpec

_M_TICKS = _metrics.counter(
    "glt.fleet.controller_ticks", "fleet controller evaluation passes")
_M_SHED_BCASTS = _metrics.counter(
    "glt.fleet.shed_broadcasts",
    "fleet_shed alert broadcasts (firing + resolved transitions)")
_M_POSTMORTEMS = _metrics.counter(
    "glt.fleet.postmortems", "merged postmortems written")
# The fleet-aggregate instruments the SLO specs evaluate (mirrored from
# replica serving_stats deltas every tick):
_M_FLEET_ADMITTED = _metrics.counter(
    "glt.fleet.requests_total",
    "requests admitted across all replicas (mirrored)")
_M_FLEET_REJECTED = _metrics.counter(
    "glt.fleet.rejected_total",
    "requests rejected across all replicas (mirrored)")
_G_FLEET_EWMA = _metrics.gauge(
    "glt.fleet.ewma_batch_ms",
    "worst replica's EWMA micro-batch service time (mirrored)")
_G_FLEET_HIT_RATE = _metrics.gauge(
    "glt.fleet.seed_cache_hit_rate",
    "mean live-replica seed-affinity cache hit rate (mirrored)")


def default_fleet_specs(reject_budget: float = 0.10,
                        batch_ms: float = 250.0,
                        windows: Tuple[Tuple[float, float], ...]
                        = DEFAULT_WINDOWS) -> List[SloSpec]:
    """The fleet-wide objectives: bounded structured-rejection budget
    and bounded service time, both over the mirrored aggregates."""
    return [
        SloSpec(name="fleet_rejects",
                metric="glt.fleet.rejected_total", kind="ratio",
                denom="glt.fleet.requests_total",
                objective=reject_budget, comparison="<=",
                windows=windows),
        SloSpec(name="fleet_latency",
                metric="glt.fleet.ewma_batch_ms", kind="gauge",
                objective=batch_ms, comparison="<=",
                windows=windows),
    ]


@dataclasses.dataclass
class FleetSpec:
    """Controller policy: objectives + cadence + postmortem sink.

    Attributes:
      slos: the fleet-wide :class:`SloSpec` set (None = defaults).
      poll_interval_s: tick cadence when :meth:`FleetController.start`
        runs the loop on a thread.
      replica_deadline_s: how long a replica may fail its stats pull
        before the controller declares it dead.
      postmortem_dir: where merged postmortems land; None defers to
        ``GLT_FLIGHT_DIR`` and finally the working directory.
      stats_timeout_s: per-pull wire timeout (bounded, always).
    """

    slos: Optional[Sequence[SloSpec]] = None
    poll_interval_s: float = 1.0
    replica_deadline_s: float = 3.0
    postmortem_dir: Optional[str] = None
    stats_timeout_s: float = 2.0


class FleetController:
    """Watch N replicas, evaluate one SLO set, shed/reopen fleet-wide.

    Args:
      replica_addrs: the fleet's ``(host, port)`` list.
      spec: a :class:`FleetSpec` policy bundle.
      router: optional :class:`~glt_tpu.serving.router.FleetRouter` —
        when given, the controller registers for its death reports (so
        a transport-detected kill triggers the same postmortem as a
        heartbeat expiry) and broadcasts shed through it; otherwise the
        controller uses its own control connections.
    """

    def __init__(self, replica_addrs: Sequence[Tuple[str, int]],
                 spec: Optional[FleetSpec] = None, router=None,
                 name: str = "fleet-controller"):
        # The controller IS the observability opt-in for a fleet: burn
        # evaluation reads the local instrument registry, so mirroring
        # requires the process-wide metrics switch on (same pattern as
        # DistServer(enable_metrics=True)).
        _metrics.enable()
        self.spec = spec or FleetSpec()
        self.name = name
        self.router = router
        self._lock = threading.Lock()
        self._dead: set = set()
        self._last: Dict[str, dict] = {}
        self._postmortems: List[str] = []
        self._conns: Dict[str, RemoteServerConnection] = {}
        for i, (host, port) in enumerate(replica_addrs):
            self._conns[f"{host}:{port}"] = RemoteServerConnection(
                (host, port), max_retries=0, seed=2000 + i)
        self.supervisor = Supervisor(
            deadline_secs=self.spec.replica_deadline_s,
            on_dead=self._on_replica_dead)
        for key in self._conns:
            self.supervisor.register(key)
        self.monitor = SloMonitor(
            list(self.spec.slos) if self.spec.slos is not None
            else default_fleet_specs(),
            interval_s=self.spec.poll_interval_s,
            on_alert=self._on_alert)
        if router is not None:
            router.on_dead = self._router_dead
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- per-tick work ------------------------------------------------------
    def _poll_replica(self, key: str) -> Optional[dict]:
        """One replica's ``serving_stats`` + ``fleet_health`` pull;
        None on any failure (the missed beat is the signal)."""
        conn = self._conns[key]
        t = self.spec.stats_timeout_s
        stats = conn.request(op="serving_stats", _retries=0, _timeout=t)
        health = conn.request(op="fleet_health", _retries=0, _timeout=t)
        return {"stats": stats, "health": health}

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One full evaluation pass; returns the SLO alerts emitted.
        Deterministic given the replicas' responses and ``now``."""
        _M_TICKS.inc()
        admitted_delta = 0
        rejected_delta = 0
        ewma_worst = 0.0
        hit_rates: List[float] = []
        stale_peers: List[str] = []
        for key in list(self._conns):
            with self._lock:
                if key in self._dead:
                    continue
            try:
                pulled = self._poll_replica(key)
            except Exception:  # noqa: BLE001 — silence IS the signal
                continue
            if pulled is None:
                continue
            self.supervisor.beat(key)
            stats = pulled.get("stats") or {}
            if stats.get("enabled"):
                prev = self._last.get(key) or {}
                admitted_delta += max(
                    0, int(stats.get("completed", 0))
                    - int(prev.get("completed", 0)))
                rejected = (int(stats.get("rejected_overload", 0))
                            + int(stats.get("rejected_deadline", 0))
                            + int(stats.get("rejected_shed", 0)))
                prev_rejected = (int(prev.get("rejected_overload", 0))
                                 + int(prev.get("rejected_deadline", 0))
                                 + int(prev.get("rejected_shed", 0)))
                rejected_delta += max(0, rejected - prev_rejected)
                ewma_worst = max(ewma_worst,
                                 float(stats.get("ewma_batch_ms", 0.0)))
                hit_rates.append(
                    float(stats.get("seed_cache_hit_rate", 0.0)))
                self._last[key] = stats
            # Consume the structured staleness verdict each replica
            # publishes about ITS peers (satellite: stale_after_s).
            for peer, st in (pulled.get("health") or {}).get(
                    "peers", {}).items():
                if float(st.get("stale_after_s", 1.0)) <= 0:
                    stale_peers.append(f"{key}/{peer}")
        _M_FLEET_ADMITTED.inc(admitted_delta)
        _M_FLEET_REJECTED.inc(rejected_delta)
        _G_FLEET_EWMA.set(round(ewma_worst, 3))
        if hit_rates:
            _G_FLEET_HIT_RATE.set(
                round(sum(hit_rates) / len(hit_rates), 6))
        if stale_peers:
            _flight.record("fleet.stale_peers", peers=stale_peers[:16])
        return self.monitor.tick(now)

    # -- alerting -----------------------------------------------------------
    def _on_alert(self, alert: dict) -> None:
        """A fleet SLO transitioned: broadcast shed/reopen everywhere."""
        _M_SHED_BCASTS.inc()
        _flight.record("fleet.shed_broadcast", slo=alert.get("slo"),
                       state=alert.get("state"),
                       shed_frac=alert.get("shed_frac"))
        if self.router is not None:
            self.router.broadcast_shed(alert)
            return
        for key, conn in self._conns.items():
            with self._lock:
                if key in self._dead:
                    continue
            try:
                conn.request(op="fleet_shed", alert=dict(alert),
                             _retries=0,
                             _timeout=self.spec.stats_timeout_s)
            except Exception:  # noqa: BLE001 — legacy/dead tolerated
                continue

    # -- death + postmortem -------------------------------------------------
    def _router_dead(self, key: str, reason: str) -> None:
        """Router seam: a transport-detected death reaches the same
        postmortem path as a heartbeat expiry."""
        self._replica_died(key, {"reason": reason, "source": "router"})

    def _on_replica_dead(self, key: str, report: dict) -> None:
        self._replica_died(key, dict(report, source="supervisor"))

    def _replica_died(self, key: str, report: dict) -> None:
        with self._lock:
            if key in self._dead:
                return
            self._dead.add(key)
        _flight.record("fleet.replica_dead", replica=key, **{
            k: v for k, v in report.items()
            if k in ("reason", "source", "silent_s", "deadline_s")})
        if self.router is not None:
            # Idempotent: no-op when the router already re-homed.
            self.router.mark_dead(key, reason="controller")
        try:
            self.postmortem(reason=f"replica_dead:{key}")
        except Exception:  # noqa: BLE001 — the controller must live
            _flight.record("fleet.postmortem_failed", replica=key)

    def _postmortem_dir(self) -> str:
        return (self.spec.postmortem_dir
                or os.environ.get("GLT_FLIGHT_DIR") or ".")

    def postmortem(self, reason: str) -> Optional[str]:
        """Pull every reachable replica's flight ring, add this
        process's own, and write one merged dump.  Returns the merged
        path (None only if nothing could be collected)."""
        outdir = self._postmortem_dir()
        os.makedirs(outdir, exist_ok=True)
        _flight.record("fleet.postmortem_start", reason=reason)
        paths: List[str] = []
        for key, conn in self._conns.items():
            with self._lock:
                if key in self._dead:
                    continue
            try:
                resp = conn.request(op="flight_dump", _retries=0,
                                    _timeout=self.spec.stats_timeout_s)
                dump = resp.get("flight")
            except Exception:  # noqa: BLE001 — dead replicas skip
                continue
            if not dump:
                continue
            # Attribute the stream: the merged postmortem keys events
            # by (pid, role), and single-host fleets share a pid — the
            # replica key in the role is what keeps N replicas' rings
            # distinguishable (and the merge validator satisfied).
            dump = dict(dump)
            dump["role"] = f"{dump.get('role') or 'replica'}@{key}"
            p = os.path.join(
                outdir,
                f"glt_fleet_pm-{key.replace(':', '_')}.json")
            tmp = p + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dump, f)
            os.replace(tmp, p)
            paths.append(p)
        own = _flight.dump_now(
            f"fleet_postmortem:{reason}",
            path=os.path.join(outdir, "glt_fleet_pm-controller.json"))
        if own:
            paths.append(own)
        if not paths:
            return None
        merged = os.path.join(outdir, "glt_fleet_postmortem.json")
        _flight.merge_flight_dumps(paths, out=merged)
        _M_POSTMORTEMS.inc()
        _flight.record("fleet.postmortem", reason=reason, out=merged,
                       sources=len(paths))
        with self._lock:
            self._postmortems.append(merged)
        return merged

    # -- introspection / lifecycle ------------------------------------------
    def status(self) -> dict:
        """Controller view: supervisor table + SLO states + postmortem
        artifacts written so far."""
        with self._lock:
            dead = sorted(self._dead)
            postmortems = list(self._postmortems)
        return {"replicas": self.supervisor.status(),
                "dead": dead,
                "slo": self.monitor.states(),
                "firing": self.monitor.firing(),
                "postmortems": postmortems}

    def start(self) -> "FleetController":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="glt-fleet-controller")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.spec.poll_interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must live
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0 + self.spec.poll_interval_s)
        self.supervisor.stop()
        self.monitor.stop()
        for conn in self._conns.values():
            conn.close()
