"""Structured serving errors — the backpressure/SLO vocabulary.

Every failure the serving path can inflict on a client is one of these,
each carrying a stable wire ``code`` so the structured-error protocol
(``{"error": ..., "code": ...}`` responses, dist_server.py) round-trips
them losslessly: a client can distinguish "back off and retry"
(:class:`Overloaded`, with a ``retry_after_ms`` hint) from "your request
was too late" (:class:`DeadlineExceeded`) from "the engine broke under
you" (plain :class:`ServingError`) without parsing message text.

Deliberately dependency-free (stdlib only): imported by both endpoints —
``distributed.dist_client`` maps error responses back through
:func:`error_from_response` — without dragging jax into either.
"""
from __future__ import annotations

from typing import Optional


class ServingError(RuntimeError):
    """A serving request failed server-side (engine fault, shutdown).

    The generic member of the family; subclasses refine the wire code.
    ``retry_after_ms`` is an optional backoff hint (only
    :class:`Overloaded` populates it today).
    """

    code = "serving_failed"

    def __init__(self, message: str,
                 retry_after_ms: Optional[float] = None):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms


class Overloaded(ServingError):
    """Admission control rejected the request: the bounded inflight
    queue is full.  Back off for ~``retry_after_ms`` and retry — the
    rejection is the server protecting its SLO for accepted requests,
    not a failure of this one."""

    code = "overloaded"


class DeadlineExceeded(ServingError):
    """The request missed its deadline before (or while) being served;
    the coalescer dropped it rather than spend a device slot on an
    answer nobody is waiting for."""

    code = "deadline_exceeded"


class BadRequest(ServingError):
    """The request itself is invalid (empty/oversized seed set, ids out
    of range).  Never retried — the same request will always fail."""

    code = "bad_request"


class ServingDisabled(ServingError):
    """The server was started without ``serving=ServingOptions(...)``."""

    code = "serving_disabled"


class ServingDown(ServingError):
    """The serving front is stopped or its dispatcher died."""

    code = "serving_down"


class ServingTimeout(ServingError):
    """The connection handler gave up waiting for the coalescer —
    server-side wait budget exhausted (distinct from the client's own
    socket timeout)."""

    code = "serving_timeout"


class NoHealthyReplica(ServingError):
    """The fleet router exhausted its bounded failover budget: the
    request's shard owner and its successor(s) were all dead or
    unreachable.  Structured by design — a whole-fleet outage surfaces
    as this error after a bounded number of jittered retries, never as
    a hang or a raw socket traceback (the ``bounded_get`` discipline
    applied to the client path)."""

    code = "no_healthy_replica"


_BY_CODE = {cls.code: cls for cls in (
    ServingError, Overloaded, DeadlineExceeded, BadRequest,
    ServingDisabled, ServingDown, ServingTimeout, NoHealthyReplica)}

#: Wire codes this module owns; ``RemoteServerConnection`` routes error
#: responses with these codes through :func:`error_from_response`.
SERVING_CODES = frozenset(_BY_CODE)


def error_from_response(resp: dict) -> ServingError:
    """Rebuild the typed error from a structured error response."""
    cls = _BY_CODE.get(str(resp.get("code")), ServingError)
    return cls(str(resp.get("error", "serving request failed")),
               retry_after_ms=resp.get("retry_after_ms"))
