"""Fleet routing: partition-affinity sharding + exactly-once failover.

The routing tier in front of N serving replicas (docs/serving.md
"Fleet").  Two pieces:

* :class:`ShardTable` — a pure routing table.  Seed ids hash into
  ``num_shards`` shards (multiplicative hash: stable across runs and
  decorrelated from any structure in the id space); shards are assigned
  to replicas by LPT greedy bin-packing over the **partition frequency
  scores** (:func:`glt_tpu.partition.residency_scores` — the same
  access-probability oracle that drives DRAM feature staging).  Each
  replica therefore owns a stable, load-balanced slice of the id space,
  and its seed-affinity LRU (``seed_cache_hit_rate`` in
  ``serving_stats``) sees the same hot ids request after request — hit
  rate becomes a property of *routing*, not luck.

* :class:`FleetRouter` — the live tier.  Health is active probing
  through a :class:`~glt_tpu.distributed.supervisor.Supervisor`
  (``fleet_health`` probes beat the table; the structured
  ``stale_after_s`` verdict is consumed in :meth:`fleet_status`); a
  replica that dies — by missed deadline or by a transport error on the
  data path — has its shards re-homed to the survivors, and the
  in-flight request **fails over exactly once** to the new owner after
  one jittered backoff.  Structured serving errors (``Overloaded``,
  ``BadRequest``, ...) are NEVER failed over: the replica spoke clearly,
  and re-sending would risk a duplicate response.  When the failover
  target also fails at transport level, the caller gets a structured
  :class:`~glt_tpu.serving.errors.NoHealthyReplica` — bounded retries,
  typed errors, never a hang (the ``bounded_get`` discipline applied to
  the client path).

Mixed-version contract: a pre-fleet replica answers the ``fleet_hello``
handshake with its unknown-op fatal error; the router marks it *legacy*
and degrades it to direct routing — it still serves ``subgraph_request``
and ``fleet_health``, it just never receives fleet control ops
(``fleet_shed``).
"""
from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..distributed.dist_client import RemoteServerConnection
from ..distributed.supervisor import Supervisor
from ..obs import flight as _flight
from ..obs import metrics as _metrics
from .client import InferenceClient, retryable_transport
from .errors import NoHealthyReplica, ServingError

_M_REQUESTS = _metrics.counter(
    "glt.fleet.requests", "requests routed through the fleet tier")
_M_FAILOVERS = _metrics.counter(
    "glt.fleet.failovers",
    "in-flight requests failed over after a transport error")
_M_REHOMED = _metrics.counter(
    "glt.fleet.rehomed_shards",
    "shards re-homed off dead replicas")
_M_LEGACY = _metrics.counter(
    "glt.fleet.legacy_replicas",
    "replicas degraded to direct routing (pre-fleet protocol)")
_M_EXHAUSTED = _metrics.counter(
    "glt.fleet.no_healthy_replica",
    "requests that exhausted the bounded failover budget")
_G_HEALTHY = _metrics.gauge(
    "glt.fleet.healthy_replicas", "replicas currently routable")

# Knuth's multiplicative constant (2^32 / phi): consecutive ids — the
# common "hot block" layout after frequency reordering — land in
# different shards, so one replica never inherits a whole hot block.
_HASH_MULT = np.uint64(2654435761)
_HASH_MASK = np.uint64(0xFFFFFFFF)


def shard_of(ids, num_shards: int) -> np.ndarray:
    """Vectorized stable shard assignment for int64 node ids."""
    a = np.asarray(ids, dtype=np.int64).ravel()
    h = (a.astype(np.uint64) * _HASH_MULT) & _HASH_MASK
    return (h % np.uint64(int(num_shards))).astype(np.int64)


class ShardTable:
    """Shard -> replica assignment balanced over residency scores.

    Pure data structure (no I/O, no threads — the router serializes
    access under its own lock).  ``scores`` is the per-node access
    probability/score vector from the frequency partitioner
    (:func:`glt_tpu.partition.residency_scores`); ``None`` means
    uniform load, which degrades LPT to round-robin-by-size.
    """

    def __init__(self, replicas: Sequence[str], num_shards: int = 64,
                 scores=None):
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("ShardTable needs at least one replica")
        if len(set(self.replicas)) != len(self.replicas):
            raise ValueError(f"duplicate replica keys: {self.replicas!r}")
        self.num_shards = int(num_shards)
        if self.num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.scores = (None if scores is None
                       else np.asarray(scores, np.float64).ravel())
        if self.scores is not None and self.scores.size:
            # Expected load per shard: the summed score mass of the ids
            # hashing into it — what LPT balances across replicas.
            self.shard_load = np.bincount(
                shard_of(np.arange(self.scores.size), self.num_shards),
                weights=self.scores, minlength=self.num_shards)
        else:
            self.shard_load = np.ones(self.num_shards, np.float64)
        self._dead: set = set()
        self._assign: Dict[int, str] = {}
        self._assign_lpt(range(self.num_shards), self.replicas)

    def _assign_lpt(self, shards, replicas: Sequence[str]) -> None:
        """Greedy LPT: hottest unassigned shard to least-loaded replica
        (deterministic: ties break toward earlier shards/replicas)."""
        loads = {r: 0.0 for r in replicas}
        for s, r in self._assign.items():
            if r in loads:
                loads[r] += float(self.shard_load[s])
        for s in sorted(shards,
                        key=lambda s: (-float(self.shard_load[s]), s)):
            target = min(replicas, key=lambda r: loads[r])
            self._assign[int(s)] = target
            loads[target] += float(self.shard_load[s])

    # -- routing ------------------------------------------------------------
    def owner(self, shard: int) -> str:
        return self._assign[int(shard)]

    def route(self, seeds) -> str:
        """Replica key owning this request: the shard of its hottest
        seed (by residency score; first seed when scores are uniform),
        so a multi-seed request lands where most of its reuse is."""
        a = np.asarray(seeds, dtype=np.int64).ravel()
        if a.size == 0:
            raise ValueError("cannot route an empty seed set")
        pick = int(a[0])
        if self.scores is not None and self.scores.size and a.size > 1:
            s = np.where((a >= 0) & (a < self.scores.size),
                         self.scores[np.clip(a, 0,
                                             self.scores.size - 1)], 0.0)
            pick = int(a[int(np.argmax(s))])
        return self.owner(int(shard_of([pick], self.num_shards)[0]))

    def rehome(self, replica: str) -> List[int]:
        """Mark ``replica`` dead and reassign its shards to survivors
        (LPT against their CURRENT loads, so re-homing stays balanced).
        Idempotent; returns the re-homed shard ids (empty when there is
        no survivor to take them — the caller's NoHealthyReplica case).
        """
        if replica in self._dead:
            return []
        self._dead.add(replica)
        survivors = [r for r in self.replicas if r not in self._dead]
        moved = sorted(s for s, r in self._assign.items() if r == replica)
        if not survivors:
            return []
        for s in moved:
            del self._assign[s]
        self._assign_lpt(moved, survivors)
        return moved

    def live_replicas(self) -> List[str]:
        return [r for r in self.replicas if r not in self._dead]

    def assignment(self) -> Dict[int, str]:
        return dict(self._assign)

    def shards_of(self, replica: str) -> List[int]:
        return sorted(s for s, r in self._assign.items() if r == replica)


class FleetRouter:
    """Route subgraph requests across N serving replicas.

    Args:
      replica_addrs: ``(host, port)`` per replica, order = identity.
      scores: per-node residency scores (the partition oracle) steering
        both shard load balancing and hottest-seed routing; None =
        uniform.
      num_shards: routing granularity (shards per fleet, not per
        replica); more shards = smoother re-homing at a little more
        table.
      policy: ``"affinity"`` (the shard table) or ``"random"`` —
        uniform-random over live replicas, the A/B baseline whose
        cache churn the bench measures against.
      health_deadline_s / probe_interval_s: supervisor deadline and
        active-probe cadence for replica health.
      backoff_base / backoff_cap: the jittered-backoff parameters for
        the failover hand-off (PR 4 semantics).
      start_probes: tests drive health transitions deterministically by
        passing False and calling :meth:`mark_dead` themselves.
    """

    def __init__(self, replica_addrs: Sequence[Tuple[str, int]],
                 scores=None, num_shards: int = 64,
                 policy: str = "affinity", name: str = "router",
                 request_timeout: float = 1.0,
                 op_timeout_margin: float = 30.0,
                 health_deadline_s: float = 2.0,
                 probe_interval_s: Optional[float] = None,
                 backoff_base: float = 0.05, backoff_cap: float = 0.5,
                 seed: int = 0, start_probes: bool = True):
        if policy not in ("affinity", "random"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self.name = name
        self.policy = policy
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._dead: set = set()
        self._legacy: set = set()
        #: controller seam: called as ``on_dead(replica_key, reason)``
        #: AFTER re-homing, from whichever thread detected the death.
        self.on_dead = None

        keys: List[str] = []
        self._clients: Dict[str, InferenceClient] = {}
        self._control: Dict[str, RemoteServerConnection] = {}
        for i, (host, port) in enumerate(replica_addrs):
            key = f"{host}:{port}"
            keys.append(key)
            # Data path: max_retries=0 — the router owns retry policy,
            # and a connection-level resend would break the
            # exactly-once-failover accounting.
            self._clients[key] = InferenceClient(
                (host, port), timeout=request_timeout,
                op_timeout_margin=op_timeout_margin,
                max_retries=0, seed=seed + i)
            # Control path: its own connection, so a probe can never
            # desync a data stream mid-subgraph-frame.
            self._control[key] = RemoteServerConnection(
                (host, port), max_retries=0,
                backoff_base=backoff_base, backoff_cap=backoff_cap,
                seed=seed + 1000 + i)
        self.table = ShardTable(keys, num_shards=num_shards,
                                scores=scores)
        _G_HEALTHY.set(len(keys))
        self.supervisor = Supervisor(deadline_secs=health_deadline_s,
                                     on_dead=self._supervisor_dead)
        for key in keys:
            self._hello(key)
        if start_probes:
            for key in keys:
                self.supervisor.watch(
                    key, probe=self._make_probe(key),
                    interval=probe_interval_s)

    # -- protocol negotiation ----------------------------------------------
    def _hello(self, key: str) -> None:
        """One ``fleet_hello`` handshake; a fatal unknown-op answer (or
        an unreachable replica) degrades the replica to legacy direct
        routing — it keeps serving subgraphs, it never gets fleet
        control ops."""
        try:
            resp = self._control[key].request(
                op="fleet_hello", peer=self.name, _retries=0,
                _timeout=5.0)
            protocol = int(resp.get("protocol", 0))
        except (RuntimeError, OSError):
            protocol = 0
        if protocol < 1:
            with self._lock:
                self._legacy.add(key)
            _M_LEGACY.inc()
            _flight.record("fleet.legacy_replica", replica=key)

    # -- health -------------------------------------------------------------
    def _make_probe(self, key: str):
        conn = self._control[key]

        def probe():
            # fleet_health predates the fleet tier, so the same probe
            # covers legacy replicas; an exception here is swallowed by
            # Supervisor.watch and the missed beat IS the signal.
            conn.request(op="fleet_health", _retries=0, _timeout=2.0)

        return probe

    def _supervisor_dead(self, replica: str, report: dict) -> None:
        self.mark_dead(replica, reason="heartbeat_deadline")

    def mark_dead(self, replica: str, reason: str = "manual") -> List[int]:
        """Declare a replica dead and re-home its shards (idempotent).
        Fired by the supervisor deadline, by a data-path transport
        error, or directly by tests/operators."""
        with self._lock:
            if replica in self._dead:
                return []
            self._dead.add(replica)
            moved = self.table.rehome(replica)
            healthy = len(self.table.live_replicas())
            successors = sorted({self.table.owner(s) for s in moved})
        _G_HEALTHY.set(healthy)
        _M_REHOMED.inc(len(moved))
        _flight.record("fleet.replica_dead", replica=replica,
                       reason=reason, healthy_replicas=healthy)
        _flight.record("fleet.rehome", replica=replica,
                       shards=len(moved), successors=successors)
        if self.on_dead is not None:
            try:
                self.on_dead(replica, reason)
            except Exception:  # noqa: BLE001 — routing must survive it
                pass
        return moved

    # -- routing ------------------------------------------------------------
    def _pick(self, seeds, exclude: Tuple[str, ...] = ()) -> str:
        with self._lock:
            live = [k for k in self.table.live_replicas()
                    if k not in exclude]
            if not live:
                _M_EXHAUSTED.inc()
                raise NoHealthyReplica(
                    f"no healthy replica left for this request "
                    f"(fleet of {len(self.table.replicas)}, "
                    f"dead={sorted(self._dead)})")
            if self.policy == "random":
                return self._rng.choice(live)
            key = self.table.route(seeds)
            # Post-rehome the table only maps to live replicas, but an
            # excluded (just-failed, not yet declared) owner falls back
            # to its successor-by-hash deterministically.
            if key in exclude:
                key = live[int(shard_of([int(np.asarray(seeds).ravel()
                                             [0])],
                                        len(live))[0])]
            return key

    def _jitter(self, attempt: int) -> float:
        base = min(self.backoff_cap, self.backoff_base * (2 ** attempt))
        return base * (0.5 + 0.5 * self._rng.random())

    def subgraph(self, seeds, timeout: Optional[float] = None):
        """Route one ego-subgraph request; fail over at most once.

        Outcomes, exhaustively: a correct batch from the shard owner; a
        correct batch from its successor after ONE transport-error
        failover; a structured :class:`ServingError` relayed from
        whichever replica answered; or :class:`NoHealthyReplica` when
        the bounded failover budget is exhausted.  Structured errors
        are never failed over — the replica answered, and a re-send
        could produce a duplicate response.
        """
        _M_REQUESTS.inc()
        primary = self._pick(seeds)
        try:
            return self._clients[primary].subgraph(seeds,
                                                   timeout=timeout)
        except ServingError:
            raise
        except Exception as exc:  # noqa: BLE001 — classified below
            if not retryable_transport(exc):
                raise
            first = exc
        # Transport failure: the replica is gone as far as this request
        # is concerned.  Declare it (re-homes its shards for everyone),
        # one jittered backoff, then exactly one hand-off.
        self.mark_dead(primary, reason="transport_error")
        _M_FAILOVERS.inc()
        time.sleep(self._jitter(0))
        successor = self._pick(seeds, exclude=(primary,))
        _flight.record("fleet.failover", dead=primary,
                       successor=successor,
                       seeds=int(np.asarray(seeds).size))
        try:
            return self._clients[successor].subgraph(seeds,
                                                     timeout=timeout)
        except ServingError:
            raise
        except Exception as exc:  # noqa: BLE001 — classified below
            if not retryable_transport(exc):
                raise
            self.mark_dead(successor, reason="transport_error")
            _M_EXHAUSTED.inc()
            raise NoHealthyReplica(
                f"failover exhausted: shard owner {primary} and "
                f"successor {successor} both failed at transport level "
                f"({type(first).__name__}, then "
                f"{type(exc).__name__})") from exc

    # -- fleet control ------------------------------------------------------
    def broadcast_shed(self, alert: dict) -> Dict[str, Optional[dict]]:
        """Deliver one SLO alert dict (``slo_alert`` schema) to every
        live fleet-protocol replica; legacy/dead replicas are skipped
        or tolerated (None in the result)."""
        out: Dict[str, Optional[dict]] = {}
        with self._lock:
            targets = [k for k in self.table.live_replicas()
                       if k not in self._legacy]
        for key in targets:
            try:
                out[key] = self._control[key].request(
                    op="fleet_shed", alert=dict(alert), _retries=0,
                    _timeout=2.0)
            except Exception:  # noqa: BLE001 — best-effort broadcast
                out[key] = None
        return out

    # -- introspection ------------------------------------------------------
    def fleet_status(self) -> Dict[str, dict]:
        """Per-replica health table.  ``suspect`` consumes the
        supervisor's structured ``stale_after_s`` verdict (negative =
        past its heartbeat deadline) instead of re-deriving the
        deadline math here."""
        sup = self.supervisor.status()
        with self._lock:
            return {
                key: {
                    "alive": key not in self._dead,
                    "legacy": key in self._legacy,
                    "shards": len(self.table.shards_of(key)),
                    "suspect": float(
                        sup.get(key, {}).get("stale_after_s", 1.0)) <= 0,
                    "supervisor": sup.get(key),
                }
                for key in self.table.replicas
            }

    def replica_stats(self) -> Dict[str, Optional[dict]]:
        """Each live replica's ``serving_stats`` table (None where the
        pull failed) — the controller's and the bench's raw material."""
        with self._lock:
            targets = list(self.table.live_replicas())
        out: Dict[str, Optional[dict]] = {}
        for key in targets:
            try:
                out[key] = self._control[key].request(
                    op="serving_stats", _retries=0, _timeout=2.0)
            except Exception:  # noqa: BLE001 — a dead replica reads None
                out[key] = None
        return out

    def legacy_replicas(self) -> List[str]:
        with self._lock:
            return sorted(self._legacy)

    def close(self) -> None:
        self.supervisor.stop()
        for client in self._clients.values():
            client.close()
        for conn in self._control.values():
            conn.close()
