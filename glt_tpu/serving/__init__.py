"""glt_tpu.serving — low-latency multi-tenant inference serving.

The "millions of users" half of the north star (ROADMAP item 3): many
concurrent clients each request ego-subgraphs for small seed sets and
get back the sampled batch (node ids, COO, edge ids, features) at
interactive latency.  The throughput comes from **cross-request
micro-batching**: a coalescer packs outstanding requests into one
fixed-shape device batch (padding buckets, so no recompiles), runs the
shared sample->dedup->gather program once, and scatters results back
per client; admission control bounds inflight work and rejects overload
with structured ``Overloaded`` + retry-after instead of queueing
without bound.

Layers (see docs/serving.md):
  errors     typed structured errors + wire-code round-tripping
  options    ServingOptions — coalescing policy + admission bounds
  engine     SubgraphEngine — bucketed device programs + per-request split
  front      ServingFront — admission queue + coalescing dispatcher
  client     InferenceClient — thin request client w/ per-op timeouts
  router     ShardTable + FleetRouter — partition-affinity routing,
             replica health, exactly-once failover
  fleet      FleetSpec + FleetController — fleet-wide SLO shed/reopen,
             merged postmortems on replica death

Server side, pass ``init_server(dataset, serving=ServingOptions(...))``;
the ``subgraph_request`` wire op and ``serving_stats`` live on the same
framed protocol the training loaders use.
"""
from .client import InferenceClient, retryable_transport
from .engine import CoalescedSample, SubgraphEngine
from .errors import (
    BadRequest,
    DeadlineExceeded,
    NoHealthyReplica,
    Overloaded,
    ServingDisabled,
    ServingDown,
    ServingError,
    ServingTimeout,
    error_from_response,
)
from .fleet import FleetController, FleetSpec, default_fleet_specs
from .front import ServingFront
from .options import ServingOptions
from .router import FleetRouter, ShardTable

__all__ = [
    "BadRequest",
    "CoalescedSample",
    "DeadlineExceeded",
    "FleetController",
    "FleetRouter",
    "FleetSpec",
    "InferenceClient",
    "NoHealthyReplica",
    "Overloaded",
    "ServingDisabled",
    "ServingDown",
    "ServingError",
    "ServingFront",
    "ServingOptions",
    "ServingTimeout",
    "ShardTable",
    "SubgraphEngine",
    "default_fleet_specs",
    "error_from_response",
    "retryable_transport",
]
