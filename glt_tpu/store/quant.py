"""Compressed row codecs for the feature tiers: bf16 and per-column int8.

Every feature tier — HBM hot table, DRAM stager, disk store — is
bandwidth-bound (r05 roofline; GIDS and PyTorch-Direct in PAPERS.md
reach the same conclusion), so bytes-per-row is the one knob that
multiplies *capacity and throughput at all three levels at once*.  This
module is the single sanctioned place where feature bytes are narrowed:

* ``bf16`` — dtype widening only.  Each f32 value is rounded to its
  nearest bfloat16 (8-bit mantissa); decode is a plain ``astype`` back
  to f32.  2x smaller rows, no calibration state.
* ``int8`` — per-column affine quantization.  Column ``j`` stores
  ``q = clip(round((x - zero[j]) / scale[j]), -127, 127)`` with
  ``scale = (cmax - cmin) / 253`` computed over the column in float64
  and ``zero`` the column midpoint SNAPPED to an exact multiple of
  ``scale`` (``zero = k * scale`` for integer ``k``).  4x smaller rows;
  ``scale`` and ``zero`` ride in the store manifest.

Error contract (tested in ``tests/test_quant.py``): for every in-range
value, ``|x - dequantize(quantize(x))| <= scale[j] / 2`` per column up
to f32 representation error (relative ``2**-23`` of the decoded value)
— the half-step bound of round-to-nearest; 253 levels (not 254) keep
the bound valid at the column extremes despite the snapped midpoint.  A
constant column has ``scale == 0`` and round-trips *exactly* (``q ==
0``, ``dq == zero``).

Decode has exactly one formula per codec, shared verbatim by the Pallas
on-chip epilogue and the XLA fallback so the A/B seam stays
bit-identical:

* widen (bf16):  ``x.astype(float32)``  — NOT ``x * 1 + 0``, which
  would flip ``-0.0`` to ``+0.0``.
* affine (int8): ``where(scale > 0, (x.astype(float32) + k) * scale,
  zero)`` with ``k = rint(zero / scale)`` the integer-valued f32
  zero point.

The affine form is add-then-multiply BY DESIGN: ``x * scale + zero``
is FMA-contractable, and XLA contracts it into a single-rounding fused
op in some program contexts but not others (measured: the Pallas
interpret arm and the post-gather arm disagreed by 1 ulp, and
``lax.optimization_barrier`` does not block contraction).  No hardware
fuses ``(a + b) * c``, so every rounding step of the add-then-mul form
is forced and the two seam arms agree bit-for-bit on every backend.
``zero`` snapped to ``k * scale`` is what makes the two forms
equivalent; ``|k|`` is clamped to ``2**23`` so ``q + k`` stays exact in
f32 (a column whose offset/step ratio exceeds that is outside int8's
representable regime anyway).

``dequantize(0)`` for int8 is ``zero``, not 0 — padding rows must be
zeroed AFTER dequantization everywhere (the gather epilogues and the
tiered merge both do).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
import ml_dtypes
import numpy as np

#: Supported row codecs. "raw" is the identity (storage dtype == logical
#: dtype); the compressed codecs always decode to float32.
CODECS = ("raw", "bf16", "int8")

# Quantized range: symmetric [-127, 127].  253 levels (not 254) leave a
# half-step of headroom at each extreme, so the snapped zero point never
# pushes round() past ±127 and the scale/2 bound holds at cmin/cmax.
_QMAX = 127.0
_QLEVELS = 253.0
# |k| cap keeping q + k exact in f32 (see module docstring).
_KMAX = float(2 ** 23)


class QuantSpec(NamedTuple):
    """Everything needed to decode one store's rows.

    ``scale``/``zero`` are ``[dim]`` float32 vectors for ``int8`` and
    ``None`` otherwise.  ``logical_dtype`` is what decode produces
    (always float32 for the compressed codecs).
    """

    codec: str
    logical_dtype: np.dtype
    scale: Optional[np.ndarray] = None
    zero: Optional[np.ndarray] = None

    @property
    def is_compressed(self) -> bool:
        return self.codec != "raw"


def storage_dtype(codec: str, logical_dtype) -> np.dtype:
    """The on-disk / on-wire element dtype for ``codec``."""
    if codec == "raw":
        return np.dtype(logical_dtype)
    if codec == "bf16":
        return np.dtype(ml_dtypes.bfloat16)
    if codec == "int8":
        return np.dtype(np.int8)
    raise ValueError(f"unknown feature codec {codec!r}; expected {CODECS}")


def raw_spec(logical_dtype) -> QuantSpec:
    return QuantSpec("raw", np.dtype(logical_dtype))


def encode(array: np.ndarray, codec: str) -> tuple:
    """Encode ``array`` (``[N, d]`` float) under ``codec``.

    Returns ``(encoded, spec)`` where ``encoded`` has the storage dtype
    and ``spec`` is the :class:`QuantSpec` that decodes it.
    """
    array = np.asarray(array)
    if codec == "raw":
        return array, raw_spec(array.dtype)
    if codec == "bf16":
        return (array.astype(ml_dtypes.bfloat16),
                QuantSpec("bf16", np.dtype(np.float32)))
    if codec == "int8":
        spec = calibrate_int8(array)
        return quantize_int8(array, spec), spec
    raise ValueError(f"unknown feature codec {codec!r}; expected {CODECS}")


def calibrate_int8(array: np.ndarray) -> QuantSpec:
    """Per-column affine parameters over the full matrix, in float64.

    ``zero`` is the column midpoint snapped to an exact integer multiple
    of the f32 ``scale`` (module docstring: what makes the
    contraction-proof decode form equivalent to ``q * scale + zero``).
    """
    a = np.asarray(array, np.float64)
    if a.size == 0:
        d = a.shape[1] if a.ndim == 2 else 0
        return QuantSpec("int8", np.dtype(np.float32),
                         np.zeros(d, np.float32), np.zeros(d, np.float32))
    cmin = a.min(axis=0)
    cmax = a.max(axis=0)
    scale = ((cmax - cmin) / _QLEVELS).astype(np.float32)
    s64 = scale.astype(np.float64)
    mid = (cmax + cmin) / 2.0
    k = np.where(s64 > 0.0, np.rint(mid / np.where(s64 > 0.0, s64, 1.0)),
                 0.0)
    k = np.clip(k, -_KMAX, _KMAX)
    # k * s64 is exact in f64 (|k| <= 2^23, s has 24 significant bits);
    # the f32 cast is the single rounding decode reproduces.
    zero = np.where(s64 > 0.0, (k * s64).astype(np.float32),
                    mid.astype(np.float32))
    return QuantSpec("int8", np.dtype(np.float32),
                     scale, zero.astype(np.float32))


def zero_point(spec: QuantSpec) -> np.ndarray:
    """The integer-valued f32 ``k`` with ``zero == k * scale`` per column.

    Recovered from the manifest pair by one correctly-rounded division:
    ``zero = fl(k * scale)`` is within ``eps * |k|`` of ``k * scale``,
    so ``rint(zero / scale)`` lands back on ``k`` exactly.
    """
    scale = np.asarray(spec.scale, np.float64)
    zero = np.asarray(spec.zero, np.float64)
    safe = np.where(scale > 0.0, scale, 1.0)
    k = np.where(scale > 0.0, np.rint(zero / safe), 0.0)
    return np.clip(k, -_KMAX, _KMAX).astype(np.float32)


def quantize_int8(array: np.ndarray, spec: QuantSpec) -> np.ndarray:
    """``[N, d]`` float -> int8 codes under ``spec`` (host-side)."""
    a = np.asarray(array, np.float64)
    scale = np.asarray(spec.scale, np.float64)
    zero = np.asarray(spec.zero, np.float64)
    # Constant columns (scale == 0) always encode to 0 (decode == zero).
    safe = np.where(scale > 0.0, scale, 1.0)
    q = np.rint((a - zero) / safe)
    q = np.where(scale > 0.0, q, 0.0)
    return np.clip(q, -_QMAX, _QMAX).astype(np.int8)


def encode_with_spec(rows: np.ndarray, spec: QuantSpec) -> np.ndarray:
    """Encode ``rows`` under an already-fixed ``spec`` (streaming writes)."""
    rows = np.asarray(rows)
    if spec.codec == "raw":
        return np.ascontiguousarray(rows, spec.logical_dtype)
    if spec.codec == "bf16":
        return np.ascontiguousarray(rows).astype(ml_dtypes.bfloat16)
    if spec.codec == "int8":
        return quantize_int8(rows, spec)
    raise ValueError(f"unknown feature codec {spec.codec!r}")


def decode(encoded: np.ndarray, spec: QuantSpec) -> np.ndarray:
    """Host-side decode — numpy mirror of :func:`dequantize`."""
    if spec.codec == "raw":
        return np.asarray(encoded)
    if spec.codec == "bf16":
        return np.asarray(encoded).astype(np.float32)
    if spec.codec == "int8":
        scale = np.asarray(spec.scale, np.float32)
        zero = np.asarray(spec.zero, np.float32)
        k = zero_point(spec)
        wide = (np.asarray(encoded).astype(np.float32) + k) * scale
        return np.where(scale > 0.0, wide, zero)
    raise ValueError(f"unknown feature codec {spec.codec!r}")


def dequantize(x, spec: QuantSpec):
    """THE device-side decode formula (jnp), shared by both seam arms.

    The Pallas epilogue kernels inline exactly these expressions; the
    XLA fallback calls this function on the gathered rows.  Any edit
    here must be mirrored in ``ops/gather_pallas.py`` /
    ``ops/fused_frontier.py`` or the cross-arm bit tests fail.
    """
    if spec.codec == "raw":
        return x
    if spec.codec == "bf16":
        return x.astype(jnp.float32)
    if spec.codec == "int8":
        scale = jnp.asarray(spec.scale, jnp.float32)
        zero = jnp.asarray(spec.zero, jnp.float32)
        k = jnp.asarray(zero_point(spec))
        # Add-then-mul: contraction-proof, so every rounding is forced
        # and both seam arms agree bit-for-bit (module docstring).
        wide = (x.astype(jnp.float32) + k) * scale
        return jnp.where(scale > 0.0, wide, zero)
    raise ValueError(f"unknown feature codec {spec.codec!r}")


#: Sublane count of the packed scale/zero kernel input: the f32 tiling
#: floor (8, 128), so the block passes GLT019 without a special case.
SCALE_ZERO_ROWS = 8


def scale_zero_rows(spec: QuantSpec, dim: int) -> np.ndarray:
    """``[8, dim]`` f32 kernel input: row 0 = scale, row 1 = zero,
    row 2 = the integer zero point ``k`` (:func:`zero_point`).

    The dequant epilogue kernels take the affine vectors as one VMEM
    block; a ``(3, d)`` block would violate the f32 sublane floor
    (GLT019), so they ride in the first rows of an 8-row tile.  For the
    widen codec the block is (1, 0, 0) so the same kernel signature
    serves both modes.
    """
    out = np.zeros((SCALE_ZERO_ROWS, dim), np.float32)
    if spec.codec == "int8":
        out[0, :] = np.asarray(spec.scale, np.float32)
        out[1, :] = np.asarray(spec.zero, np.float32)
        out[2, :] = zero_point(spec)
    else:
        out[0, :] = 1.0
    return out


def spec_to_manifest(spec: QuantSpec) -> dict:
    """Manifest fragment for a compressed store (empty for raw)."""
    if spec.codec == "raw":
        return {}
    out = {"codec": spec.codec}
    if spec.codec == "int8":
        out["quant"] = {
            "scale": [float(v) for v in np.asarray(spec.scale)],
            "zero": [float(v) for v in np.asarray(spec.zero)],
        }
    return out


def spec_from_manifest(man: dict) -> QuantSpec:
    """Decode spec from a store manifest (handles legacy raw manifests)."""
    codec = man.get("codec", "raw")
    logical = np.dtype(man["dtype"])
    if codec == "raw":
        return QuantSpec("raw", logical)
    if codec == "bf16":
        return QuantSpec("bf16", logical)
    if codec == "int8":
        q = man.get("quant") or {}
        return QuantSpec(
            "int8", logical,
            np.asarray(q.get("scale", []), np.float32),
            np.asarray(q.get("zero", []), np.float32))
    raise ValueError(f"unknown feature codec {codec!r} in manifest")
