"""Disk-resident feature tier: raw row-major file + checksummed manifest.

The third storage tier of the engine (docs/storage.md): below the HBM
hot tier (:mod:`glt_tpu.data.feature_cache`) and the host-DRAM cold tier
(:class:`~glt_tpu.parallel.dist_feature.HostColdStore`) sits an
NVMe/disk-backed store holding the FULL feature matrix, so "features >>
DRAM" (GIDS / PyTorch-Direct scale, PAPERS.md) stops being a
constructor-time constraint.

Layout is deliberately dumb: one ``features.bin`` of C-contiguous
``[num_rows, dim]`` rows next to one ``manifest.json`` carrying dtype,
shape, a format version and the file's sha256.  Dumb layout is what makes
the serving path fast — a row read is one offset computation and one
page-cache copy, no decompression, no framing; the OS page cache IS the
block cache and :class:`~glt_tpu.store.stager.DramStager` is the
explicitly-budgeted row cache above it.

Publish discipline is the GLT011 contract (``glt_tpu/ckpt/store.py``):
the store directory is fully written under a private ``.tmp-*`` name and
published with ONE ``os.replace``; a writer SIGKILLed mid-write leaves
only a tmp directory readers never open.  Torn *disk* state after
publish (truncation, bit rot) surfaces as a structured
:class:`StoreCorruptError` — at open time via the cheap size check, and
on demand via :meth:`DiskFeatureStore.verify` (full checksum).

Reads go through ``np.memmap`` fancy indexing in row chunks: numpy
releases the GIL during the copy, so chunks fan out across a
ThreadPoolExecutor exactly like ``HostColdStore.serve_into`` — the same
``(pool, row_chunk)`` contract, one tier further down.  Fault injection
(:class:`~glt_tpu.testing.faults.FaultPlan` ``fail_disk_read_at`` /
``delay_disk_read``) hooks every chunk read, so the chaos suite can
place a read error or stall at an exact point in an epoch.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
from typing import Optional

import numpy as np

from glt_tpu.store import quant

FORMAT_VERSION = 1
DATA_NAME = "features.bin"
MANIFEST_NAME = "manifest.json"


class StoreError(RuntimeError):
    """Feature-store read/write failed (missing, malformed, out of range)."""


class StoreCorruptError(StoreError):
    """The store file contradicts its manifest: truncated or bit-rotted.

    Raised at open time (size mismatch) or by :meth:`DiskFeatureStore.
    verify` (checksum mismatch).  Structured by design — a corrupt tier
    must never surface as a zero-row batch."""


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    # Best-effort directory fsync (some filesystems refuse dir fds).
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_feature_store(root: str, array: np.ndarray, codec: str = "raw",
                        overwrite: bool = False) -> str:
    """Write ``array`` (``[N, d]``) as a feature store directory at ``root``.

    Atomic publish (GLT011): everything lands under ``.tmp-<pid>`` next
    to ``root`` and ONE ``os.replace`` makes it visible.  Returns
    ``root``.

    Args:
      codec: row encoding — ``"raw"`` stores ``array`` bit-exactly;
        ``"bf16"``/``"int8"`` compress through :mod:`glt_tpu.store.
        quant` (manifest records the codec and, for int8, the
        per-column scale/zero).  The manifest ``dtype`` is always the
        LOGICAL dtype readers decode to.
      overwrite: with an existing ``root``, ``False`` (the default)
        refuses; ``True`` publishes over it atomically — the new tree
        is fully written under ``.tmp-*``, the old root is moved aside
        to a ``.trash-*`` sibling, the tmp is renamed in, and the trash
        is deleted.  Readers see either the complete old store or the
        complete new one, never a mix.
    """
    array = np.asarray(array)
    if array.ndim == 1:
        array = array[:, None]
    if array.ndim != 2:
        raise StoreError(
            f"feature store rows must be [N, d]; got shape {array.shape}")
    root = os.path.abspath(root)
    if os.path.exists(root) and not overwrite:
        raise StoreError(f"feature store target already exists: {root}")
    encoded, spec = quant.encode(array, codec)
    parent = os.path.dirname(root) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f".tmp-{os.path.basename(root)}-{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    data_path = os.path.join(tmp, DATA_NAME)
    np.ascontiguousarray(encoded).tofile(data_path)
    manifest = {
        "format_version": FORMAT_VERSION,
        "dtype": np.dtype(spec.logical_dtype).str,
        "shape": [int(array.shape[0]), int(array.shape[1])],
        "sha256": _sha256(data_path),
    }
    manifest.update(quant.spec_to_manifest(spec))
    with open(os.path.join(tmp, MANIFEST_NAME), "w") as fh:
        json.dump(manifest, fh)
        fh.flush()
        os.fsync(fh.fileno())
    with open(data_path, "rb") as fh:
        os.fsync(fh.fileno())
    _fsync_dir(tmp)
    if os.path.exists(root):
        trash = os.path.join(
            parent, f".trash-{os.path.basename(root)}-{os.getpid()}")
        os.replace(root, trash)
        os.replace(tmp, root)
        shutil.rmtree(trash, ignore_errors=True)
    else:
        os.replace(tmp, root)
    _fsync_dir(parent)
    return root


class DiskFeatureStore:
    """mmap-served row reads over one published feature-store directory.

    The disk-level analogue of :class:`~glt_tpu.parallel.dist_feature.
    HostColdStore`: :meth:`gather_into` has the same ``(out, row_ids,
    pool, row_chunk)`` shape and the same GIL-releasing chunked-copy
    behavior, one tier down.  Thread-safe: the byte counters are
    lock-protected and the memmap is read-only.

    Args:
      root: published store directory (``features.bin`` + manifest).
      faults: optional :class:`~glt_tpu.testing.faults.FaultPlan`; its
        ``on_disk_read`` hook fires before every chunk read.
      verify: checksum the data file against the manifest at open
        (full-file read — the cheap size check always runs).
    """

    def __init__(self, root: str, faults=None, verify: bool = False):
        self.root = os.path.abspath(root)
        mpath = os.path.join(self.root, MANIFEST_NAME)
        try:
            with open(mpath) as fh:
                man = json.load(fh)
        except (OSError, ValueError) as e:
            raise StoreError(f"unreadable store manifest {mpath}: {e}")
        if man.get("format_version") != FORMAT_VERSION:
            raise StoreError(
                f"store format {man.get('format_version')!r} != "
                f"{FORMAT_VERSION} at {self.root}")
        # ``dtype`` is the STORAGE dtype (what features.bin holds and
        # what flows through memmap reads, stager buffers and device
        # transfers); ``logical_dtype`` is what rows decode to.  For a
        # raw store the two coincide and nothing changes.
        self.codec = man.get("codec", "raw")
        self.logical_dtype = np.dtype(man["dtype"])
        try:
            self.dtype = quant.storage_dtype(self.codec, self.logical_dtype)
        except ValueError as e:
            raise StoreError(f"bad store manifest {mpath}: {e}")
        self._quant_spec = quant.spec_from_manifest(man)
        shape = man["shape"]
        self.num_rows, self.dim = int(shape[0]), int(shape[1])
        self.row_nbytes = self.dim * self.dtype.itemsize
        if (self.codec == "int8"
                and len(np.asarray(self._quant_spec.scale)) != self.dim):
            raise StoreError(
                f"int8 store manifest {mpath} carries "
                f"{len(np.asarray(self._quant_spec.scale))} scale entries "
                f"for dim {self.dim}")
        self.sha256 = man["sha256"]
        self._data_path = os.path.join(self.root, DATA_NAME)
        expected = self.num_rows * self.row_nbytes
        try:
            actual = os.path.getsize(self._data_path)
        except OSError as e:
            raise StoreError(f"missing store data file: {e}")
        if actual != expected:
            raise StoreCorruptError(
                f"store data file {self._data_path} holds {actual} bytes, "
                f"manifest says {expected} ([{self.num_rows}, {self.dim}] "
                f"{self.dtype}) — truncated or torn")
        self.faults = faults
        self._arr: Optional[np.ndarray] = None
        self._lock = threading.Lock()
        self.bytes_read = 0
        self.chunk_reads = 0

    def verify(self) -> None:
        """Full checksum against the manifest (reads the whole file)."""
        got = _sha256(self._data_path)
        if got != self.sha256:
            raise StoreCorruptError(
                f"store data file {self._data_path} sha256 {got[:12]}… != "
                f"manifest {self.sha256[:12]}… — bit rot or torn write")

    @property
    def shape(self):
        return (self.num_rows, self.dim)

    @property
    def is_compressed(self) -> bool:
        return self.codec != "raw"

    def quant_spec(self) -> "quant.QuantSpec":
        """The :class:`~glt_tpu.store.quant.QuantSpec` decoding this store."""
        return self._quant_spec

    def _mapped(self) -> np.ndarray:
        """The read-only memmap view, created lazily (one per store)."""
        if self._arr is None:
            self._arr = np.memmap(self._data_path, dtype=self.dtype,
                                  mode="r", shape=(self.num_rows, self.dim))
        return self._arr

    def _read_chunk(self, out: np.ndarray, sel: np.ndarray,
                    row_ids: np.ndarray, lo: int, hi: int) -> None:
        """One GIL-releasing page-cache copy of rows ``sel[lo:hi]``."""
        if self.faults is not None:
            self.faults.on_disk_read()
        arr = self._mapped()
        idx = sel[lo:hi]
        out[idx] = arr[row_ids[idx]]
        with self._lock:
            self.bytes_read += int(idx.size) * self.row_nbytes
            self.chunk_reads += 1

    def gather_into(self, out: np.ndarray, row_ids: np.ndarray,
                    pool=None, row_chunk: int = 16384) -> list:
        """Gather ``row_ids`` (< 0 = skip) into ``out`` rows, row-chunked.

        Same contract as ``HostColdStore.serve_into``: with ``pool`` the
        read splits into ``row_chunk``-row work items and returns their
        futures (caller awaits); without, it runs inline and returns
        ``[]``.  Out-of-range ids raise a structured :class:`StoreError`
        before any byte moves.
        """
        row_ids = np.asarray(row_ids)
        sel = np.where(row_ids >= 0)[0]
        if sel.size == 0:
            return []
        mx = int(row_ids[sel].max())
        if mx >= self.num_rows:
            raise StoreError(
                f"row id {mx} out of range for {self.num_rows}-row store "
                f"{self.root}")
        if pool is None:
            self._read_chunk(out, sel, row_ids, 0, sel.size)
            return []
        return [pool.submit(self._read_chunk, out, sel, row_ids,
                            lo, min(lo + row_chunk, sel.size))
                for lo in range(0, sel.size, row_chunk)]

    def read_rows(self, row_ids: np.ndarray) -> np.ndarray:
        """``[len(row_ids), dim]`` rows (zeros at ids < 0)."""
        row_ids = np.asarray(row_ids)
        out = np.zeros((row_ids.shape[0], self.dim), self.dtype)
        self.gather_into(out, row_ids)
        return out

    def __repr__(self) -> str:
        return (f"DiskFeatureStore(shape={self.shape}, dtype={self.dtype}, "
                f"codec={self.codec!r}, root={self.root!r})")


class FeatureStoreWriter:
    """Streaming range writer for a feature store: sweeps land in place,
    :meth:`finalize` checksums and atomically publishes.

    The refresh driver writes one node partition at a time, so the full
    ``[N, d]`` output never materializes in memory: rows land directly
    in a memmapped data file under a DETERMINISTIC ``.partial-<name>``
    sibling of ``root`` (no pid — a restarted writer re-attaches to the
    same partial file).  Resume safety comes from idempotence, not
    journaling: sweeps cover disjoint row ranges and encoding is a pure
    function of ``(rows, spec)``, so rewriting a range after a crash is
    bit-identical and the final sha256 matches an uninterrupted run.

    Publish keeps the GLT011 discipline: readers only ever see ``root``
    appear via ``os.replace``; the partial directory is never a valid
    store (no manifest until finalize writes one as its last act).

    ``int8`` needs an explicit pre-calibrated :class:`~glt_tpu.store.
    quant.QuantSpec` (calibration is a whole-matrix reduction a
    streaming writer cannot do); ``raw``/``bf16`` need none.
    """

    def __init__(self, root: str, num_rows: int, dim: int,
                 logical_dtype=np.float32, codec: str = "raw",
                 spec: Optional["quant.QuantSpec"] = None,
                 overwrite: bool = False):
        self.root = os.path.abspath(root)
        if os.path.exists(self.root) and not overwrite:
            raise StoreError(
                f"feature store target already exists: {self.root}")
        self.num_rows, self.dim = int(num_rows), int(dim)
        if spec is None:
            if codec == "int8":
                raise StoreError(
                    "int8 streaming writes need an explicit QuantSpec "
                    "(per-column calibration is a whole-matrix pass)")
            spec = (quant.raw_spec(logical_dtype) if codec == "raw"
                    else quant.QuantSpec(codec, np.dtype(np.float32)))
        self.codec = spec.codec
        self.spec = spec
        self.storage_dtype = quant.storage_dtype(self.codec,
                                                 spec.logical_dtype)
        self._overwrite = overwrite
        parent = os.path.dirname(self.root) or "."
        os.makedirs(parent, exist_ok=True)
        self._tmp = os.path.join(
            parent, f".partial-{os.path.basename(self.root)}")
        os.makedirs(self._tmp, exist_ok=True)
        self._data_path = os.path.join(self._tmp, DATA_NAME)
        nbytes = self.num_rows * self.dim * self.storage_dtype.itemsize
        reattach = (os.path.exists(self._data_path)
                    and os.path.getsize(self._data_path) == nbytes)
        self._mm = np.memmap(self._data_path, dtype=self.storage_dtype,
                             mode="r+" if reattach else "w+",
                             shape=(self.num_rows, self.dim))
        self.reattached = reattach
        self._finalized = False

    def write_rows(self, lo: int, rows: np.ndarray) -> None:
        """Encode and land ``rows`` at row offset ``lo`` (idempotent)."""
        if self._finalized:
            raise StoreError("write_rows after finalize")
        rows = np.asarray(rows)
        hi = lo + rows.shape[0]
        if lo < 0 or hi > self.num_rows or rows.shape[1] != self.dim:
            raise StoreError(
                f"write_rows range [{lo}, {hi}) x {rows.shape[1]} out of "
                f"bounds for [{self.num_rows}, {self.dim}] store")
        self._mm[lo:hi] = quant.encode_with_spec(rows, self.spec)

    def flush(self) -> None:
        """Flush landed rows to the partial file (checkpoint barrier:
        a resumed writer re-attaches to everything flushed here)."""
        if not self._finalized:
            self._mm.flush()

    def abort(self) -> None:
        """Drop the partial tree (nothing was ever visible at root)."""
        self._mm = None
        shutil.rmtree(self._tmp, ignore_errors=True)

    def finalize(self) -> str:
        """Flush, checksum, write the manifest and publish atomically."""
        if self._finalized:
            return self.root
        self._mm.flush()
        self._mm = None
        with open(self._data_path, "rb") as fh:
            os.fsync(fh.fileno())
        manifest = {
            "format_version": FORMAT_VERSION,
            "dtype": np.dtype(self.spec.logical_dtype).str,
            "shape": [self.num_rows, self.dim],
            "sha256": _sha256(self._data_path),
        }
        manifest.update(quant.spec_to_manifest(self.spec))
        with open(os.path.join(self._tmp, MANIFEST_NAME), "w") as fh:
            json.dump(manifest, fh)
            fh.flush()
            os.fsync(fh.fileno())
        _fsync_dir(self._tmp)
        parent = os.path.dirname(self.root) or "."
        if os.path.exists(self.root):
            if not self._overwrite:
                raise StoreError(
                    f"feature store target appeared during write: "
                    f"{self.root}")
            trash = os.path.join(
                parent,
                f".trash-{os.path.basename(self.root)}-{os.getpid()}")
            os.replace(self.root, trash)
            os.replace(self._tmp, self.root)
            shutil.rmtree(trash, ignore_errors=True)
        else:
            os.replace(self._tmp, self.root)
        _fsync_dir(parent)
        self._finalized = True
        return self.root
