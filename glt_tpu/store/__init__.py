"""glt_tpu.store — disk-backed third feature tier with async DRAM prefetch.

The storage stack below the HBM hot tier and the host-DRAM cold tier
(docs/storage.md):

* :class:`DiskFeatureStore` / :func:`write_feature_store` — a raw
  row-major file + checksummed manifest (GLT011 atomic publish), served
  through mmap with GIL-releasing row-chunked reads;
* :class:`DramStager` — a bounded, *enforced* DRAM budget filled ahead
  of the sampler by async staging threads under a BGL-style frequency
  residency policy, with the partition book's access statistics as the
  prefetch oracle (:meth:`DramStager.warm`);
* :class:`DiskColdStore` — the ``HostColdStore`` drop-in that slots the
  disk tier under :class:`~glt_tpu.parallel.dist_train.
  TieredTrainPipeline` and the fused scanned epoch unchanged;
* :func:`publish_store_stats` — ``glt.store.*`` gauges through the obs
  registry;
* :mod:`~glt_tpu.store.quant` — the bf16/int8 row codecs: compressed
  bytes flow through every tier and widen to f32 on-chip in the gather
  epilogues (docs/storage.md "Compressed tiers").
"""
from .disk import (
    DATA_NAME,
    FORMAT_VERSION,
    MANIFEST_NAME,
    DiskFeatureStore,
    FeatureStoreWriter,
    StoreCorruptError,
    StoreError,
    write_feature_store,
)
from .quant import CODECS, QuantSpec, dequantize
from .stager import DiskColdStore, DramStager, publish_store_stats

__all__ = [
    "CODECS",
    "DATA_NAME",
    "FORMAT_VERSION",
    "MANIFEST_NAME",
    "DiskFeatureStore",
    "FeatureStoreWriter",
    "QuantSpec",
    "StoreCorruptError",
    "StoreError",
    "dequantize",
    "write_feature_store",
    "DiskColdStore",
    "DramStager",
    "publish_store_stats",
]
