"""Bounded-DRAM staging cache over a :class:`~glt_tpu.store.disk.DiskFeatureStore`.

``DramStager`` is the middle of the three-tier read path (docs/storage.md):

    HBM hot prefix / cold cache  →  **DRAM stage (this)**  →  disk store

Its contract is an *explicit, enforced* DRAM budget: the one feature-byte
allocation is ``[capacity, dim]`` with ``capacity = dram_budget_bytes //
row_nbytes``, sized at construction and never grown — "features >> DRAM"
is therefore testable on any machine by handing a small budget to a big
store.  (Residency metadata — a slot map over store rows — costs ~12
bytes/row on top; it scales with the *store*, not the budget, and is
documented out of the budget.)

Residency is the BGL-style frequency policy: every row carries an access
count (seeded by the prefetch oracle — partition-book access
probabilities from :func:`glt_tpu.partition.frequency_partitioner.
residency_scores` via :meth:`warm`), rows are admitted on demand or by
:meth:`stage_ahead`, and eviction always takes the lowest-scoring
resident slots, so frequently-touched rows (power-law hubs, the
proximity set the oracle ranks) converge to DRAM while the long tail
faults to disk.

Failure semantics (the chaos contract, tests/test_store.py):

* a **stalled staging thread** degrades, never hangs: :meth:`gather`
  NEVER waits on staging — rows not yet resident are demand-faulted
  synchronously from disk (correct bytes, degraded latency);
* a **failed staging read** is swallowed into ``stage_errors`` (the
  stager keeps operating in degraded synchronous-fetch mode);
* a **failed demand read** raises the store's structured error out of
  :meth:`gather` — never a silent zero-row batch.

Counters (``bytes_from_dram`` / ``bytes_from_disk``, hit/miss, stage
depth) publish through the obs registry as ``glt.store.*`` gauges
(:func:`publish_store_stats`), the same host-side pattern as
``feature_cache.publish_cache_stats``.
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Optional

import numpy as np

from ..obs import device as _device
from ..obs import flight as _flight
from ..obs import metrics as _metrics
from .disk import DiskFeatureStore


class DramStager:
    """Explicitly-budgeted DRAM row cache with async stage-ahead.

    Args:
      store: the backing :class:`DiskFeatureStore`.
      dram_budget_bytes: hard cap on resident feature bytes; capacity is
        ``budget // row_nbytes`` rows (must be >= 1).
      stage_threads: workers for :meth:`stage_ahead` staging reads.
      row_chunk: chunk width for fanned disk reads.
    """

    def __init__(self, store: DiskFeatureStore, dram_budget_bytes: int,
                 stage_threads: int = 1, row_chunk: int = 16384):
        self.store = store
        self.dram_budget_bytes = int(dram_budget_bytes)
        self.row_chunk = int(row_chunk)
        cap = self.dram_budget_bytes // store.row_nbytes
        if cap < 1:
            raise ValueError(
                f"dram_budget_bytes={dram_budget_bytes} holds zero "
                f"{store.row_nbytes}-byte rows; raise the budget")
        self.capacity = min(cap, store.num_rows)
        # THE feature-byte allocation — never grown (the enforced budget).
        self._buf = np.empty((self.capacity, store.dim), store.dtype)
        assert self._buf.nbytes <= self.dram_budget_bytes
        # Any device-resident copy of a staged block carries this
        # fingerprint; the device census then attributes it to us.
        _device.register_owner("stager", array=self._buf)
        # Residency metadata (out of budget, documented): store row ->
        # slot, slot -> store row, slot -> score, row -> access frequency.
        self._slot_of = np.full(store.num_rows, -1, np.int64)
        self._row_of = np.full(self.capacity, -1, np.int64)
        self._score = np.zeros(self.capacity, np.float64)
        self._freq = np.zeros(store.num_rows, np.float64)
        self._used = 0
        self._lock = threading.Lock()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, int(stage_threads)),
            thread_name_prefix="glt-store-stage")
        # Counters (all under self._lock).
        self.hits = 0
        self.misses = 0
        self.bytes_from_dram = 0
        self.bytes_from_disk = 0
        self.staged_rows = 0
        self.stage_errors = 0
        self.stage_depth = 0          # stage-ahead tasks in flight
        self.stage_depth_max = 0
        self._epoch_mark = self._counters()

    # -- residency ---------------------------------------------------------
    def resident_rows(self) -> int:
        with self._lock:
            return self._used

    def resident_bytes(self) -> int:
        return self.resident_rows() * self.store.row_nbytes

    def _install(self, row_ids: np.ndarray, rows: np.ndarray) -> int:
        """Admit ``rows`` for ``row_ids`` (parallel arrays), evicting the
        lowest-score residents when full.  Returns rows admitted."""
        with self._lock:
            row_ids, first = np.unique(row_ids, return_index=True)
            rows = rows[first]
            fresh = self._slot_of[row_ids] < 0
            row_ids, rows = row_ids[fresh], rows[fresh]
            if row_ids.size > self.capacity:
                # More new rows than the whole budget: keep the
                # highest-frequency subset (the rest re-faults to disk).
                keep = np.argsort(-self._freq[row_ids],
                                  kind="stable")[: self.capacity]
                row_ids, rows = row_ids[keep], rows[keep]
            k = row_ids.size
            if k == 0:
                return 0
            nfree = self.capacity - self._used
            take = min(k, nfree)
            n_evict = k - take
            victims = None
            if n_evict:
                # Evict the n_evict lowest-score residents — chosen from
                # the OLD resident region, before the fresh slots (whose
                # scores are stale) join it.
                victims = np.argpartition(
                    self._score[: self._used],
                    n_evict - 1)[:n_evict].astype(np.int64)
                self._slot_of[self._row_of[victims]] = -1
                _flight.record("store.evict", count=int(n_evict),
                               resident=int(self._used))
            slots = np.arange(self._used, self._used + take, dtype=np.int64)
            self._used += take
            if victims is not None:
                slots = np.concatenate([slots, victims])
            self._row_of[slots] = row_ids
            self._slot_of[row_ids] = slots
            self._score[slots] = self._freq[row_ids]
            self._buf[slots] = rows
            return k

    def warm(self, scores: np.ndarray) -> int:
        """Prefill DRAM with the top-``capacity`` rows by oracle score.

        ``scores``: ``[num_rows]`` access statistics — typically
        :func:`~glt_tpu.partition.frequency_partitioner.residency_scores`
        over the frequency partitioner's per-partition probability
        vectors.  Seeds the frequency counts, so the oracle prior also
        steers later evictions.  Returns rows staged.
        """
        scores = np.asarray(scores, np.float64)
        if scores.shape[0] != self.store.num_rows:
            raise ValueError(
                f"oracle scores cover {scores.shape[0]} rows, store has "
                f"{self.store.num_rows}")
        with self._lock:
            np.maximum(self._freq, scores, out=self._freq)
        top = np.argsort(-scores, kind="stable")[: self.capacity]
        rows = self.store.read_rows(top)
        with self._lock:
            self.bytes_from_disk += top.size * self.store.row_nbytes
        return self._install(top.astype(np.int64), rows)

    # -- the serve path ----------------------------------------------------
    def gather(self, row_ids: np.ndarray) -> np.ndarray:
        """``[len(row_ids), dim]`` rows (zeros at ids < 0); DRAM hits plus
        synchronous demand faults for the rest."""
        row_ids = np.asarray(row_ids)
        out = np.zeros((row_ids.shape[0], self.store.dim), self.store.dtype)
        self.gather_into(out, row_ids)
        return out

    def gather_into(self, out: np.ndarray, row_ids: np.ndarray,
                    pool=None, row_chunk: Optional[int] = None) -> list:
        """Serve ``row_ids`` (< 0 = skip) into ``out``: resident rows copy
        from DRAM under the lock; misses demand-fault from disk.

        With ``pool`` the miss reads fan out as chunk futures (returned —
        caller awaits, the ``serve_into`` contract); admitted misses are
        installed by a completion callback off the caller's critical
        path.  Never waits on the staging threads: a stalled stage-ahead
        degrades this call to more disk reads, not a hang.
        """
        row_ids = np.asarray(row_ids)
        sel = np.where(row_ids >= 0)[0]
        if sel.size == 0:
            return []
        ids = row_ids[sel].astype(np.int64)
        with self._lock:
            self._freq[ids] += 1.0
            slots = self._slot_of[ids]
            hit = slots >= 0
            hitpos = sel[hit]
            out[hitpos] = self._buf[slots[hit]]
            self._score[slots[hit]] = self._freq[ids[hit]]
            nh, nm = int(hit.sum()), int((~hit).sum())
            self.hits += nh
            self.misses += nm
            self.bytes_from_dram += nh * self.store.row_nbytes
            self.bytes_from_disk += nm * self.store.row_nbytes
        if nm == 0:
            return []
        misspos = sel[~hit]
        miss_req = np.full(row_ids.shape[0], -1, np.int64)
        miss_req[misspos] = ids[~hit]
        futs = self.store.gather_into(
            out, miss_req, pool=pool,
            row_chunk=row_chunk or self.row_chunk)
        if not futs:
            self._install(ids[~hit], out[misspos])
            return []
        # Install once every chunk landed.  The callback snapshots the
        # rows immediately (the caller may eventually reuse ``out`` as a
        # staging buffer; its reuse is synced batches later, but the copy
        # removes the window entirely).
        state = {"remaining": len(futs), "failed": False}
        cb_lock = threading.Lock()
        miss_ids = ids[~hit]

        def _on_chunk_done(fu):
            bad = fu.cancelled() or fu.exception() is not None
            with cb_lock:
                state["failed"] = state["failed"] or bad
                state["remaining"] -= 1
                last = state["remaining"] == 0
                failed = state["failed"]
            if last and not failed:
                # Any failed chunk vetoes the install: never cache rows a
                # read error left unfilled.
                self._install(miss_ids, np.array(out[misspos]))

        for fu in futs:
            fu.add_done_callback(_on_chunk_done)
        return futs

    # -- async stage-ahead -------------------------------------------------
    def stage_ahead(self, row_ids: np.ndarray):
        """Queue an async staging read for ``row_ids`` (the prefetch
        oracle's next-batch guess).  Returns the future (tests await it;
        production code never needs to — see the failure semantics)."""
        ids = np.unique(np.asarray(row_ids))
        ids = ids[ids >= 0].astype(np.int64)
        with self._lock:
            self.stage_depth += 1
            self.stage_depth_max = max(self.stage_depth_max,
                                       self.stage_depth)
        return self._pool.submit(self._stage, ids)

    def _stage(self, ids: np.ndarray) -> int:
        try:
            with self._lock:
                ids = ids[self._slot_of[ids] < 0]
            if ids.size == 0:
                return 0
            if ids.size > self.capacity:
                ids = ids[np.argsort(-self._freq[ids],
                                     kind="stable")[: self.capacity]]
            rows = self.store.read_rows(ids)
            with self._lock:
                self.bytes_from_disk += ids.size * self.store.row_nbytes
            n = self._install(ids, rows)
            with self._lock:
                self.staged_rows += n
            return n
        except Exception:
            # Degraded operation: the rows this read would have staged
            # will demand-fault from disk instead.  Recorded, not raised
            # (a staging thread must never take the epoch down).
            with self._lock:
                self.stage_errors += 1
            return 0
        finally:
            with self._lock:
                self.stage_depth -= 1

    # -- stats / lifecycle -------------------------------------------------
    def _counters(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "bytes_from_dram": self.bytes_from_dram,
            "bytes_from_disk": self.bytes_from_disk,
            "staged_rows": self.staged_rows,
            "stage_errors": self.stage_errors,
        }

    def stats(self) -> dict:
        """Lifetime counters + residency snapshot (host-side)."""
        with self._lock:
            c = self._counters()
            c.update({
                "capacity_rows": self.capacity,
                "resident_rows": self._used,
                "resident_bytes": self._used * self.store.row_nbytes,
                "budget_bytes": self.dram_budget_bytes,
                "stage_depth": self.stage_depth,
                "stage_depth_max": self.stage_depth_max,
            })
        total = c["hits"] + c["misses"]
        c["hit_rate"] = c["hits"] / total if total else 0.0
        return c

    def epoch_stats(self) -> dict:
        """Counters since the previous call (the per-epoch view the
        ``glt.store.*`` gauges publish), plus the residency snapshot."""
        cur = self.stats()
        with self._lock:
            mark, self._epoch_mark = self._epoch_mark, self._counters()
        out = dict(cur)
        for k, v in mark.items():
            out[k] = cur[k] - v
        total = out["hits"] + out["misses"]
        out["hit_rate"] = out["hits"] / total if total else 0.0
        return out

    def close(self) -> None:
        self._pool.shutdown(wait=False)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def publish_store_stats(stats: dict, namespace: str = "glt.store") -> dict:
    """Publish a stager stats dict as ``<namespace>.*`` gauges.

    Host-side only (GLT010); no-op overhead when metrics are disabled —
    the ``publish_cache_stats`` pattern one tier down.  Returns the
    stats dict for chaining."""
    if _metrics.enabled():
        for k, v in stats.items():
            _metrics.gauge(f"{namespace}.{k}",
                           f"glt_tpu.store tier metric {k}").set(float(v))
    return stats


class DiskColdStore:
    """Disk-backed drop-in for :class:`~glt_tpu.parallel.dist_feature.
    HostColdStore`: same ``dim`` / ``dtype`` / ``serve`` / ``serve_into``
    surface, so :class:`~glt_tpu.parallel.dist_train.TieredTrainPipeline`
    and the fused scanned epoch run unchanged on top (pass it as
    ``cold_store=``).

    The backing store holds the FULL shard-major feature matrix (row of
    shard ``s``, local row ``r`` at global row ``s * nodes_per_shard +
    r`` — the :class:`~glt_tpu.parallel.dist_feature.TieredShardedFeature`
    id layout), so one store file serves both the hot-prefix loads and
    the cold tier.  With ``dram_budget_bytes`` (or an explicit
    ``stager``) cold reads go through a shared :class:`DramStager`;
    without, every cold row reads straight from the mmap.
    """

    def __init__(self, store: DiskFeatureStore, nodes_per_shard: int,
                 hot_per_shard: int, shard_ids=None,
                 dram_budget_bytes: Optional[int] = None,
                 stager: Optional[DramStager] = None,
                 stage_threads: int = 1):
        self.store = store
        self.nodes_per_shard = int(nodes_per_shard)
        self.hot_per_shard = int(hot_per_shard)
        num_shards = store.num_rows // self.nodes_per_shard
        self.shard_ids = (tuple(range(num_shards)) if shard_ids is None
                          else tuple(shard_ids))
        self.dim = store.dim
        self.dtype = store.dtype
        if stager is None and dram_budget_bytes is not None:
            stager = DramStager(store, dram_budget_bytes,
                                stage_threads=stage_threads)
        self.stager = stager

    def serve(self, shard: int, cold_req: np.ndarray) -> np.ndarray:
        cold_req = np.asarray(cold_req)
        out = np.zeros((cold_req.shape[0], self.dim), self.dtype)
        self.serve_into(out, shard, cold_req)
        return out

    def serve_into(self, out: np.ndarray, shard: int, cold_req: np.ndarray,
                   pool=None, row_chunk: int = 16384) -> list:
        """Gather one shard's cold rows into ``out`` — the
        ``HostColdStore.serve_into`` contract served from disk/DRAM."""
        if shard not in self.shard_ids:
            raise KeyError(
                f"shard {shard} is not local to this host "
                f"(local: {self.shard_ids})")
        cold_req = np.asarray(cold_req)
        base = shard * self.nodes_per_shard + self.hot_per_shard
        req = np.where(cold_req >= 0, cold_req.astype(np.int64) + base, -1)
        if self.stager is not None:
            return self.stager.gather_into(out, req, pool=pool,
                                           row_chunk=row_chunk)
        return self.store.gather_into(out, req, pool=pool,
                                      row_chunk=row_chunk)

    def publish_epoch_stats(self, namespace: str = "glt.store") -> dict:
        """Epoch-boundary ``glt.store.*`` publication; the
        :class:`~glt_tpu.parallel.dist_train.TieredTrainPipeline` calls
        this after each ``run_epoch``."""
        if self.stager is None:
            return publish_store_stats(
                {"bytes_from_disk": self.store.bytes_read}, namespace)
        return publish_store_stats(self.stager.epoch_stats(), namespace)

    def close(self) -> None:
        if self.stager is not None:
            self.stager.close()
