"""Supervised GraphSAGE on (synthetic) ogbn-products, single TPU device.

The TPU rebuild of the reference's flagship example
(examples/train_sage_ogbn_products.py): NeighborLoader with fanout
[15, 10, 5], batch 1024, 3-layer GraphSAGE, per-epoch loss/acc + sampled
subgraphs/sec.

    python examples/train_sage_products.py --scale 0.01 --epochs 3
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import numpy as np
import optax

from examples.datasets import synthetic_products
from glt_tpu.loader import NeighborLoader
from glt_tpu.models import GraphSAGE, create_train_state, make_train_step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--frontier-cap", type=int, default=8192)
    args = ap.parse_args()

    ds, train_idx = synthetic_products(scale=args.scale)
    loader = NeighborLoader(ds, args.fanout, train_idx,
                            batch_size=args.batch_size, shuffle=True,
                            frontier_cap=args.frontier_cap)

    model = GraphSAGE(hidden_features=args.hidden, out_features=47,
                      num_layers=len(args.fanout))
    tx = optax.adam(1e-3)
    first = next(iter(loader))
    state = create_train_state(model, jax.random.PRNGKey(0), first, tx)
    step = make_train_step(model, tx, batch_size=args.batch_size)

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        n_batches, losses, accs = 0, [], []
        for batch in loader:
            state, loss, acc = step(state, batch)
            losses.append(loss)
            accs.append(acc)
            n_batches += 1
        # device_get is a true sync; block_until_ready does not
        # wait under the axon tunnel (see bench.py docstring).
        jax.device_get(losses[-1])
        dt = time.perf_counter() - t0
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"acc={float(np.mean(jax.device_get(accs))):.4f} "
              f"time={dt:.2f}s "
              f"subgraphs/s={n_batches / dt:.1f}")


if __name__ == "__main__":
    main()
