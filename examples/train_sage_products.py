"""Supervised GraphSAGE on (synthetic) ogbn-products, single TPU device.

The TPU rebuild of the reference's flagship example
(examples/train_sage_ogbn_products.py): NeighborLoader with fanout
[15, 10, 5], batch 1024, 3-layer GraphSAGE, per-epoch loss/acc + sampled
subgraphs/sec.

    python examples/train_sage_products.py --scale 0.01 --epochs 3
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import numpy as np
import optax

from examples.datasets import synthetic_products
from glt_tpu.loader import NeighborLoader
from glt_tpu.models import (
    GraphSAGE,
    create_train_state,
    make_train_step,
)
from glt_tpu.sampler import NeighborSampler


def seed_batches(train_idx, batch_size, rng):
    """Shuffled [batch_size] seed chunks, trailing batch -1 padded."""
    ids = train_idx[rng.permutation(train_idx.shape[0])]
    for lo in range(0, ids.shape[0], batch_size):
        chunk = ids[lo: lo + batch_size].astype(np.int32)
        if chunk.shape[0] < batch_size:
            chunk = np.pad(chunk, (0, batch_size - chunk.shape[0]),
                           constant_values=-1)
        yield chunk


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--frontier-cap", type=int, default=8192)
    # Occupancy-sized node capacity (VERDICT r4 #1): calibrate the padded
    # node buffer to p99 of measured unique-node counts instead of the
    # zero-dedup worst case — feature gather + train segment ops scale
    # with the padded width.  Overflow batches (<1% by construction)
    # train with their excess-node edges masked; the rate is reported.
    ap.add_argument("--auto-cap", action=argparse.BooleanOptionalAction,
                    default=True)
    ap.add_argument("--node-cap", type=int, default=None,
                    help="explicit padded node capacity (overrides "
                         "--auto-cap calibration)")
    ap.add_argument("--cap-batches", type=int, default=24,
                    help="calibration batches for --auto-cap")
    # bf16 matmuls (f32 params/aggregation/loss) — the MXU's native mixed
    # precision; loss-curve parity asserted in tests/test_models.py.
    ap.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                    default=True)
    # Fused scanned epoch (DEFAULT, the only compiled epoch driver —
    # the overlapped "train k + sample k+1" path was deleted after three
    # rounds at 0.97-0.99x; see glt_tpu/models/train.py docstring): one
    # program trains --group consecutive batches (sample+gather+fwd/bwd+
    # update under lax.scan) — amortises host dispatch + seed feeds;
    # equivalence tested exactly
    # (tests/test_models.py::test_scanned_node_step_matches_serial).
    ap.add_argument("--group", type=int, default=8,
                    help="scan G batches per program (0 = eager "
                         "two-program loader loop)")
    # Exact final-hop dedup is the default; --no-last-hop-dedup opts into
    # the leaf-block fast mode (tree-unrolled GraphSAGE semantics).
    ap.add_argument("--last-hop-dedup",
                    action=argparse.BooleanOptionalAction, default=True)
    ap.add_argument("--data-root", default=None,
                    help="dir holding converted real datasets "
                         "(scripts/convert_ogb.py); overrides "
                         "GLT_DATA_ROOT")
    args = ap.parse_args()
    if args.data_root:
        import examples.datasets as _exds

        _exds.DATA_ROOT = args.data_root

    ds, train_idx = synthetic_products(scale=args.scale)
    model = GraphSAGE(hidden_features=args.hidden, out_features=47,
                      num_layers=len(args.fanout),
                      dtype=jax.numpy.bfloat16 if args.bf16 else None)
    tx = optax.adam(1e-3)

    node_cap = args.node_cap
    probe = None
    if node_cap is None and args.auto_cap:
        from glt_tpu.sampler import calibrate_node_capacity

        probe = NeighborSampler(ds.get_graph(), args.fanout,
                                batch_size=args.batch_size,
                                frontier_cap=args.frontier_cap,
                                with_edge=False,
                                last_hop_dedup=args.last_hop_dedup)
        rng_cal = np.random.default_rng(42)
        cal = [b for b, _ in zip(
            seed_batches(train_idx, args.batch_size, rng_cal),
            range(args.cap_batches))]
        node_cap = calibrate_node_capacity(probe, cal)
        print(f"auto-cap: node_capacity {node_cap} "
              f"({node_cap / probe.full_node_capacity:.0%} of worst-case "
              f"{probe.full_node_capacity})")
        if node_cap >= probe.full_node_capacity:
            # No headroom at this scale (see BASELINE.md "Occupancy
            # finding") — reuse the probe so its compiled program serves
            # the training pipeline instead of compiling a twin.
            node_cap = None

    def build_sampler_and_state():
        from glt_tpu.models import TrainState

        sampler = probe if (probe is not None and node_cap is None) else \
            NeighborSampler(ds.get_graph(), args.fanout,
                            batch_size=args.batch_size,
                            frontier_cap=args.frontier_cap,
                            with_edge=False,
                            last_hop_dedup=args.last_hop_dedup,
                            node_capacity=node_cap)
        feat = ds.get_node_feature()
        labels = np.asarray(ds.get_node_label())
        x0 = jax.numpy.zeros((sampler.node_capacity, feat.shape[1]),
                             feat.dtype)
        ei0 = jax.numpy.full((2, sampler.edge_capacity), -1,
                             jax.numpy.int32)
        m0 = jax.numpy.zeros((sampler.edge_capacity,), bool)
        params = model.init({"params": jax.random.PRNGKey(0)}, x0, ei0, m0)
        state = TrainState(params=params, opt_state=tx.init(params),
                           step=jax.numpy.zeros((), jax.numpy.int32))
        return sampler, feat, labels, state

    if args.group > 0:
        from glt_tpu.models import (
            make_scanned_node_train_step,
            run_scanned_epoch,
        )

        sampler, feat, labels, state = build_sampler_and_state()
        sstep = make_scanned_node_train_step(
            model, tx, sampler, feat, labels, args.batch_size)
        rng = np.random.default_rng(0)

        def run_epoch(state, epoch):
            state, losses, accs, ovf = run_scanned_epoch(
                sstep, state, train_idx, args.batch_size, args.group,
                rng, jax.random.PRNGKey(100 + epoch))
            if ovf:
                print(f"  overflow batches: {ovf}/{len(losses)}")
            return state, list(losses), list(accs)
    else:
        loader = NeighborLoader(ds, args.fanout, train_idx,
                                batch_size=args.batch_size, shuffle=True,
                                frontier_cap=args.frontier_cap,
                                last_hop_dedup=args.last_hop_dedup,
                                node_capacity=node_cap)
        first = next(iter(loader))
        state = create_train_state(model, jax.random.PRNGKey(0), first, tx)
        step = make_train_step(model, tx, batch_size=args.batch_size)

        def run_epoch(state, epoch):
            losses, accs = [], []
            for batch in loader:
                state, loss, acc = step(state, batch)
                losses.append(loss)
                accs.append(acc)
            return state, losses, accs

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        state, losses, accs = run_epoch(state, epoch)
        # device_get is a true sync; block_until_ready does not
        # wait under the axon tunnel (see bench.py docstring).
        jax.device_get(losses[-1])
        dt = time.perf_counter() - t0
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"acc={float(np.mean(jax.device_get(accs))):.4f} "
              f"time={dt:.2f}s "
              f"subgraphs/s={len(losses) / dt:.1f}")


if __name__ == "__main__":
    main()
