"""End-to-end papers100M-shaped pipeline: partition -> load -> tiered train.

The full composition VERDICT round 1 found missing, mirroring the
reference's papers100M recipe (examples/distributed/: partition_ogbn_dataset
-> DistDataset.load -> dist_train_sage_supervised):

  1. offline: FrequencyPartitioner (hotness from NeighborSampler.sample_prob)
     writes the on-disk partition layout;
  2. load: DistDataset.load composes load_partition + hotness-ordered
     contiguous relabel + shard_graph / shard_feature_tiered + labels;
  3. train: host-tiered two-stage pipeline (sample jit -> threaded cold
     gather -> train jit) over the device mesh — features larger than mesh
     HBM keep the hot prefix in HBM and the cold rows in host DRAM.

papers100M itself is 111M nodes / 1.6TB features; this script runs the same
code path on a scaled synthetic graph (--scale sets the node count as a
fraction of 111M).  On a dev box:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/dist_train_papers100m.py --devices 8 --scale 2e-5

**Multi-host**: with ``GLT_NUM_PROCESSES`` set, every process joins one
global mesh (``glt_tpu.parallel.multihost``), process 0 partitions, and
each process loads ONLY its own partitions (``DistDataset.load(mesh=...)``)
— the reference's per-machine partition loading (dist_dataset.py:77-164)
over jax.distributed instead of torch RPC.  Emulate a 2-host x 4-chip pod
on a dev box with:

    scripts/run_multihost_example.sh 2 4      # procs x devices-per-proc

or manually, per process i in {0, 1}:

    GLT_NUM_PROCESSES=2 GLT_PROCESS_ID=$i \
    GLT_COORDINATOR_ADDR=localhost:9876 \
    XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
        python examples/dist_train_papers100m.py --devices 8 --scale 2e-5

On a real v5e-16 (4 hosts x 4 chips) drop the env overrides: jax
auto-detects the fleet from the TPU metadata server.
"""
import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--scale", type=float, default=2e-5,
                    help="fraction of papers100M's 111M nodes")
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--classes", type=int, default=172)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--fanout", type=int, nargs="+", default=[12, 10])
    ap.add_argument("--hot-ratio", type=float, default=0.25,
                    help="fraction of each shard's rows resident in HBM")
    ap.add_argument("--part-dir", default=None,
                    help="reuse an existing partition dir")
    ap.add_argument("--data-root", default=None,
                    help="dir holding a converted ogbn-papers100M "
                         "(scripts/convert_ogb.py ogbn); overrides "
                         "GLT_DATA_ROOT; falls back to synthetic")
    args = ap.parse_args()

    multihost_mode = int(os.environ.get("GLT_NUM_PROCESSES", "1")) > 1
    if multihost_mode:
        # Must run before anything touches the XLA backend.
        from glt_tpu.parallel import multihost

        multihost.initialize()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from glt_tpu.data import Dataset
    from glt_tpu.distributed import DistDataset
    from glt_tpu.models import GraphSAGE
    from glt_tpu.parallel import (
        DistNeighborSampler,
        TieredTrainPipeline,
        init_dist_state,
        make_dist_train_step,
        make_tiered_train_step,
    )
    from glt_tpu.partition import FrequencyPartitioner
    from glt_tpu.sampler import NeighborSampler
    from glt_tpu.sampler.base import NodeSamplerInput

    # Real converted ogbn-papers100M (scripts/convert_ogb.py) when on
    # disk; synthetic power-law graph otherwise.
    import examples.datasets as exds

    if args.data_root:
        exds.DATA_ROOT = args.data_root
    real_root = os.path.join(exds.DATA_ROOT, "ogbn-papers100M")
    if os.path.isdir(real_root):
        load = lambda f: np.load(os.path.join(real_root, f + ".npy"),
                                 mmap_mode="r")
        indptr = np.asarray(load("indptr"))
        indices = np.asarray(load("indices"))
        from glt_tpu.utils.topo import csr_to_coo

        edge_index = np.stack(csr_to_coo(indptr, indices)).astype(np.int64)
        feat = np.asarray(load("feat"), np.float32)
        labels = np.asarray(load("labels"), np.int32)
        train_idx = np.asarray(load("train_idx"))
        n = indptr.shape[0] - 1
        args.classes = int(labels.max()) + 1
        print(f"real papers100M: {n} nodes, {edge_index.shape[1]} edges")
    else:
        n = max(args.devices * args.batch_size,
                int(111_059_956 * args.scale))
        rng = np.random.default_rng(0)

        # Power-law-ish citation graph: preferential attachment by rank.
        deg_rank = rng.permutation(n)
        popularity = 1.0 / (1.0 + deg_rank.astype(np.float64)) ** 0.8
        popularity /= popularity.sum()
        avg_deg = 15
        src = rng.integers(0, n, n * avg_deg)
        dst = rng.choice(n, n * avg_deg, p=popularity)
        edge_index = np.stack([src, dst]).astype(np.int64)
        labels = (deg_rank % args.classes).astype(np.int32)
        feat = rng.normal(0, 1, (n, args.dim)).astype(np.float32)
        feat[:, 0] = labels  # learnable signal
        train_idx = rng.choice(n,
                               max(n // 10, args.devices * args.batch_size),
                               replace=False)

    is_main = (not multihost_mode) or jax.process_index() == 0
    part_dir = args.part_dir or os.path.join(
        tempfile.gettempdir(), f"glt_papers_parts_{n}_{args.devices}")
    done_file = os.path.join(part_dir, "_DONE")
    # Pre-existing partition dirs (older runs / the standalone
    # partitioner) have META.json but no sentinel: adopt, don't redo.
    if (is_main and not os.path.exists(done_file)
            and os.path.exists(os.path.join(part_dir, "META.json"))):
        with open(done_file, "w") as fh:
            fh.write("ok")
    if multihost_mode and not is_main:
        # Only process 0 partitions; everyone else waits for the sentinel
        # (the reference's rank-0 offline partition step).  NOTE:
        # part_dir must be on a filesystem all hosts share (NFS/GCS
        # mount) — on a real pod, pass --part-dir accordingly.
        deadline = time.monotonic() + 600
        while not os.path.exists(done_file):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"partitioning never finished: {part_dir} — on a "
                    f"multi-host run, --part-dir must be on a filesystem "
                    f"shared by every host")
            time.sleep(0.5)
    elif not os.path.exists(done_file):
        t0 = time.perf_counter()
        # Hotness from the sampler's access-probability estimate, one
        # vector per trainer rank (partition_ogbn_dataset.py flow).
        ds_tmp = Dataset().init_graph(edge_index, graph_mode="HOST",
                                      num_nodes=n)
        sampler = NeighborSampler(ds_tmp.get_graph(), args.fanout,
                                  batch_size=args.batch_size)
        ranks = np.array_split(train_idx, args.devices)
        probs = [np.asarray(sampler.sample_prob(r, n)) for r in ranks]
        FrequencyPartitioner(
            part_dir, args.devices, n, edge_index, node_feat=feat,
            probs=probs, cache_ratio=0.0,
            chunk_size=max(1, n // (args.devices * 16))).partition()
        # Total access probability also orders each shard's HBM prefix.
        np.save(os.path.join(part_dir, "hotness.npy"),
                np.sum(probs, axis=0))
        with open(done_file, "w") as fh:
            fh.write("ok")
        print(f"partitioned {n} nodes / {edge_index.shape[1]} edges "
              f"into {args.devices} parts in "
              f"{time.perf_counter() - t0:.1f}s -> {part_dir}")

    if multihost_mode:
        from glt_tpu.parallel import multihost

        mesh = multihost.global_mesh()
        if mesh.devices.size != args.devices:
            raise SystemExit(
                f"--devices {args.devices} != global device count "
                f"{mesh.devices.size}")
    else:
        from examples.datasets import ensure_cpu_devices

        devices = ensure_cpu_devices(args.devices)
        if len(devices) < args.devices:
            raise SystemExit(
                f"need {args.devices} devices, have {len(devices)}")
        mesh = Mesh(np.array(devices[: args.devices]), ("shard",))

    # HBM-prefix ordering by the saved total access probability (falls
    # back to in-degree inside load() when absent).  In multihost mode
    # every process loads ONLY its own partitions and feeds them into the
    # process-spanning global arrays.
    hot_file = os.path.join(part_dir, "hotness.npy")
    hotness = np.load(hot_file) if os.path.exists(hot_file) else None
    ds = DistDataset.load(part_dir, hot_ratio=args.hot_ratio, labels=labels,
                          hotness=hotness,
                          mesh=mesh if multihost_mode else None)
    tiered = args.hot_ratio < 1.0
    hot_desc = (f"{ds.feature.hot_per_shard}/{ds.feature.nodes_per_shard}"
                if tiered else "all (no host tier)")
    if is_main:
        print(f"loaded: {ds.graph.num_shards} shards x "
              f"{ds.relabel.nodes_per_shard} nodes, "
              f"hot rows/shard = {hot_desc}")

    model = GraphSAGE(hidden_features=256, out_features=args.classes,
                      num_layers=len(args.fanout), dropout_rate=0.0)
    tx = optax.adam(1e-3)
    state = init_dist_state(model, tx, ds.graph, ds.feature,
                            jax.random.PRNGKey(0), args.fanout,
                            args.batch_size)
    if tiered:
        sampler = DistNeighborSampler(ds.graph, mesh,
                                      num_neighbors=args.fanout,
                                      batch_size=args.batch_size)
        train = make_tiered_train_step(model, tx, ds.graph, ds.feature,
                                       ds.labels, mesh, args.batch_size)
        pipe = TieredTrainPipeline(sampler, train, ds.feature, mesh)

        def run_epoch(state, batches, key):
            return pipe.run_epoch(state, list(batches), key)
    else:
        step = make_dist_train_step(model, tx, ds.graph, ds.feature,
                                    ds.labels, mesh, args.fanout,
                                    args.batch_size)

        def feed(b):
            if multihost_mode:
                from glt_tpu.parallel import multihost

                return multihost.feed_seeds(b, mesh)
            return jnp.asarray(b)

        def run_epoch(state, batches, key):
            losses, accs = [], []
            for b in range(batches.shape[0]):
                state, loss, acc = step(state, feed(batches[b]),
                                        jax.random.fold_in(key, b))
                losses.append(loss)
                accs.append(acc)
            return state, losses, accs

    from glt_tpu.utils import profile

    meter = profile.ThroughputMeter()
    # One stateful Generator across epochs (identically seeded on every
    # host): each epoch draws a fresh permutation from the advancing
    # stream instead of re-deriving one from the epoch index.
    shuffle_rng = np.random.default_rng(0)
    for epoch in range(args.epochs):
        batches = ds.split_seeds(train_idx, args.batch_size, shuffle=True,
                                 rng=shuffle_rng)
        with meter.measure():
            t0 = time.perf_counter()
            state, losses, accs = run_epoch(state, batches,
                                            jax.random.PRNGKey(epoch))
            # device_get is a true sync; block_until_ready does not
            # wait under the axon tunnel (see bench.py docstring).
            jax.device_get(losses[-1])
            dt = time.perf_counter() - t0
            meter.add(subgraphs=len(losses) * args.devices)
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"acc={float(np.mean(jax.device_get(accs))):.3f} "
              f"time={dt:.2f}s "
              f"subgraphs/s={len(losses) * args.devices / dt:.1f}")
    import json
    print(json.dumps({"metric": "papers100m_loader_throughput",
                      "value": round(meter.rate("subgraphs"), 2),
                      "unit": "subgraphs/s",
                      "devices": args.devices}))


if __name__ == "__main__":
    main()
