"""Unsupervised GraphSAGE on (synthetic) PPI with negative sampling.

TPU rebuild of the reference's examples/graph_sage_unsup_ppi.py:
LinkNeighborLoader with binary negative sampling; the loss pushes linked
node embeddings together and negatives apart (binary cross-entropy on the
edge_label_index pairs).
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.datasets import synthetic_ppi
from glt_tpu.loader import LinkNeighborLoader
from glt_tpu.models import GraphSAGE
from glt_tpu.sampler import NegativeSampling


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.1)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--fanout", type=int, nargs="+", default=[10, 10])
    args = ap.parse_args()

    ds, edge_index = synthetic_ppi(scale=args.scale)
    loader = LinkNeighborLoader(
        ds, args.fanout, edge_index, batch_size=args.batch_size,
        neg_sampling=NegativeSampling("binary", 1), shuffle=True,
        frontier_cap=4096)

    model = GraphSAGE(hidden_features=64, out_features=64, num_layers=2,
                      dropout_rate=0.0)
    tx = optax.adam(1e-3)
    first = next(iter(loader))
    params = model.init({"params": jax.random.PRNGKey(0)}, first.x,
                        first.edge_index, first.edge_mask)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        eli = batch.metadata["edge_label_index"]
        label = batch.metadata["edge_label"]

        def loss_fn(p):
            z = model.apply(p, batch.x, batch.edge_index, batch.edge_mask)
            valid = (eli[0] >= 0) & (eli[1] >= 0) & (label >= 0)
            src = z[jnp.clip(eli[0], 0, z.shape[0] - 1)]
            dst = z[jnp.clip(eli[1], 0, z.shape[0] - 1)]
            logits = (src * dst).sum(-1)
            y = (label > 0).astype(jnp.float32)
            ce = optax.sigmoid_binary_cross_entropy(logits, y)
            return jnp.where(valid, ce, 0).sum() / jnp.maximum(
                valid.sum(), 1)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        losses = []
        for batch in loader:
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(loss)
        # device_get is a true sync; block_until_ready does not
        # wait under the axon tunnel (see bench.py docstring).
        jax.device_get(losses[-1])
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"time={time.perf_counter() - t0:.2f}s")


if __name__ == "__main__":
    main()
