"""Unsupervised GraphSAGE on (synthetic) PPI with negative sampling.

TPU rebuild of the reference's examples/graph_sage_unsup_ppi.py:
LinkNeighborLoader with binary negative sampling; the loss pushes linked
node embeddings together and negatives apart (binary cross-entropy on the
edge_label_index pairs).
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.datasets import synthetic_ppi
from glt_tpu.loader import LinkNeighborLoader
from glt_tpu.models import GraphSAGE
from glt_tpu.sampler import NegativeSampling


def unsup_dot_loss(z, meta):
    """Binary CE on seed-edge embedding dot products (the reference's
    unsupervised objective)."""
    eli = meta["edge_label_index"]
    label = meta["edge_label"]
    valid = (eli[0] >= 0) & (eli[1] >= 0) & (label >= 0)
    src = z[jnp.clip(eli[0], 0, z.shape[0] - 1)]
    dst = z[jnp.clip(eli[1], 0, z.shape[0] - 1)]
    logits = (src * dst).sum(-1)
    y = (label > 0).astype(jnp.float32)
    ce = optax.sigmoid_binary_cross_entropy(logits, y)
    return jnp.where(valid, ce, 0).sum() / jnp.maximum(valid.sum(), 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.1)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--fanout", type=int, nargs="+", default=[10, 10])
    # G link batches per device program (amortises dispatch — the small
    # batches here are dispatch-bound); 0 = per-batch loader loop.
    ap.add_argument("--group", type=int, default=8)
    # bf16 matmuls (f32 params/aggregation/loss); see glt_tpu/models/conv.py.
    ap.add_argument("--bf16", action="store_true")
    args = ap.parse_args()

    ds, edge_index = synthetic_ppi(scale=args.scale)
    model = GraphSAGE(dtype=jax.numpy.bfloat16 if args.bf16 else None,
                      hidden_features=64, out_features=64, num_layers=2,
                      dropout_rate=0.0)
    tx = optax.adam(1e-3)
    neg = NegativeSampling("binary", 1)

    if args.group > 0:
        from glt_tpu.models import (
            link_seed_blocks,
            make_scanned_link_train_step,
        )
        from glt_tpu.sampler import NeighborSampler

        sampler = NeighborSampler(ds.get_graph(), args.fanout,
                                  batch_size=args.batch_size,
                                  frontier_cap=4096, with_edge=False)
        feat = ds.get_node_feature()
        cap = 4 * sampler.batch_size  # binary seed union width
        import glt_tpu.sampler.neighbor_sampler as ns

        seed_width = 4 * args.batch_size
        ecap_widths = ns.hop_widths(seed_width, args.fanout, 4096)
        x0 = jnp.zeros((ns.max_sampled_nodes(seed_width, args.fanout, 4096),
                        feat.shape[1]), jnp.float32)
        ecap = sum(w * f for w, f in zip(ecap_widths, args.fanout))
        ei0 = jnp.full((2, ecap), -1, jnp.int32)
        m0 = jnp.zeros((ecap,), bool)
        params = model.init({"params": jax.random.PRNGKey(0)}, x0, ei0, m0)
        opt_state = tx.init(params)
        step = make_scanned_link_train_step(model, tx, sampler, feat,
                                            unsup_dot_loss, neg,
                                            group=args.group)
        rng = np.random.default_rng(0)

        for epoch in range(args.epochs):
            t0 = time.perf_counter()
            losses, nbs, batches = [], [], 0
            for sb, db, nb in link_seed_blocks(edge_index, args.batch_size,
                                               args.group, rng):
                params, opt_state, ls = step(
                    params, opt_state, sb, db,
                    jax.random.fold_in(jax.random.PRNGKey(epoch), batches))
                # Whole [G] blocks: per-block slices + fetches would put
                # a dispatch/round-trip per block on the critical path
                # (see glt_tpu.models.run_scanned_epoch).
                losses.append(ls)
                nbs.append(nb)
                batches += nb
            flat = np.asarray(jax.device_get(jnp.concatenate(losses)))
            valid = np.concatenate(
                [np.arange(nb) + i * args.group
                 for i, nb in enumerate(nbs)])
            mean = float(np.mean(flat[valid]))
            print(f"epoch {epoch}: loss={mean:.4f} "
                  f"time={time.perf_counter() - t0:.2f}s")
        return

    loader = LinkNeighborLoader(
        ds, args.fanout, edge_index, batch_size=args.batch_size,
        neg_sampling=neg, shuffle=True, frontier_cap=4096)
    first = next(iter(loader))
    params = model.init({"params": jax.random.PRNGKey(0)}, first.x,
                        first.edge_index, first.edge_mask)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        def loss_fn(p):
            z = model.apply(p, batch.x, batch.edge_index, batch.edge_mask)
            return unsup_dot_loss(z, batch.metadata)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        losses = []
        for batch in loader:
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(loss)
        # device_get is a true sync; block_until_ready does not
        # wait under the axon tunnel (see bench.py docstring).
        jax.device_get(losses[-1])
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"time={time.perf_counter() - t0:.2f}s")


if __name__ == "__main__":
    main()
