"""SEAL-style link prediction with induced-subgraph sampling.

TPU rebuild of the reference's examples/seal_link_pred.py: for each
candidate link, extract the induced enclosing subgraph around its
endpoints (SubGraphLoader path), label nodes by distance role (DRNL
simplified to endpoint one-hot), and classify the subgraph.
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.datasets import synthetic_ppi
from glt_tpu.loader import SubGraphLoader
from glt_tpu.models import GraphSAGE
from glt_tpu.models.conv import scatter_mean


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    # G subgraph batches per device program (amortises per-call dispatch
    # — SEAL batches are tiny); 0 = per-batch loader loop.
    ap.add_argument("--group", type=int, default=8)
    # bf16 matmuls (f32 params/aggregation/loss); see glt_tpu/models/conv.py.
    ap.add_argument("--bf16", action="store_true")
    args = ap.parse_args()

    ds, edge_index = synthetic_ppi(scale=args.scale)
    n = ds.get_graph().num_nodes
    rng = np.random.default_rng(0)

    # candidate links: half real edges (label 1), half random (label 0)
    m = 512
    pos = edge_index[:, rng.permutation(edge_index.shape[1])[:m]]
    neg = rng.integers(0, n, (2, m))
    links = np.concatenate([pos, neg], axis=1)
    labels = np.concatenate([np.ones(m), np.zeros(m)]).astype(np.int32)

    if args.group > 0:
        return run_scanned(args, ds, links, labels, rng)

    loader = SubGraphLoader(ds, [8, 8], links.T.reshape(-1),
                            batch_size=args.batch_size * 2, max_degree=16)

    model = GraphSAGE(dtype=jax.numpy.bfloat16 if args.bf16 else None,
                      hidden_features=32, out_features=32, num_layers=2,
                      dropout_rate=0.0)
    head_tx = optax.adam(1e-3)

    # seeds come in (src, dst) pairs: batch.node[2k], batch.node[2k+1]
    first = next(iter(loader))
    params = model.init({"params": jax.random.PRNGKey(0)}, first.x,
                        first.edge_index, first.edge_mask)
    w = jax.random.normal(jax.random.PRNGKey(1), (32,)) * 0.1
    opt_state = head_tx.init((params, w))

    @jax.jit
    def step(params, w, opt_state, batch, y):
        def loss_fn(pw):
            p, w = pw
            z = model.apply(p, batch.x, batch.edge_index, batch.edge_mask)
            # Seeds are deduped in the node list: locate each (src, dst)
            # endpoint by value, never positionally.
            from glt_tpu.ops.unique import relabel_by_reference

            ref = batch.node[: y.shape[0] * 2]
            si = relabel_by_reference(ref, batch.batch).reshape(
                y.shape[0], 2)
            zs = z[jnp.clip(si, 0, z.shape[0] - 1)]
            logit = ((zs[:, 0] * zs[:, 1]) @ w)
            valid = (si >= 0).all(axis=1)
            ce = optax.sigmoid_binary_cross_entropy(
                logit, y.astype(jnp.float32))
            return jnp.where(valid, ce, 0).sum() / jnp.maximum(
                valid.sum(), 1)

        loss, grads = jax.value_and_grad(loss_fn)((params, w))
        updates, opt_state = head_tx.update(grads, opt_state, (params, w))
        params, w = optax.apply_updates((params, w), updates)
        return params, w, opt_state, loss

    order = rng.permutation(2 * m)
    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        losses = []
        for lo in range(0, 2 * m, args.batch_size):
            sel = order[lo: lo + args.batch_size]
            if sel.shape[0] < args.batch_size:
                continue
            seeds = links.T[sel].reshape(-1)
            from glt_tpu.sampler import NodeSamplerInput
            out = loader.sampler.subgraph(NodeSamplerInput(seeds),
                                          max_degree=16)
            batch = loader._collate_fn(out, seeds.shape[0])
            params, w, opt_state, loss = step(
                params, w, opt_state, batch, jnp.asarray(labels[sel]))
            losses.append(loss)
        # device_get is a true sync; block_until_ready does not
        # wait under the axon tunnel (see bench.py docstring).
        jax.device_get(losses[-1])
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"time={time.perf_counter() - t0:.2f}s")


def run_scanned(args, ds, links, labels, rng):
    """G subgraph batches per program: hop expansion + induced extract +
    gather + fwd/bwd + update scanned in one jit (the per-batch loop pays
    a per-call dispatch/transfer floor the tunnel makes expensive)."""
    from glt_tpu.models import make_scanned_subgraph_train_step
    from glt_tpu.sampler import NeighborSampler

    bs, G = args.batch_size, args.group
    seed_width = bs * 2
    sampler = NeighborSampler(ds.get_graph(), [8, 8],
                              batch_size=seed_width, with_edge=True)
    feat = ds.get_node_feature()
    model = GraphSAGE(dtype=jax.numpy.bfloat16 if args.bf16 else None,
                      hidden_features=32, out_features=32, num_layers=2,
                      dropout_rate=0.0)
    tx = optax.adam(1e-3)

    def loss_fn(z, out, y):
        # Seeds are deduped in the node list: locate each (src, dst)
        # pair through seed_index, never positionally.
        si = out.metadata["seed_index"].reshape(y.shape[0], 2)
        zs = z[jnp.clip(si, 0, z.shape[0] - 1)]      # [B, 2, d]
        logit = (zs[:, 0] * zs[:, 1]).sum(-1)
        valid = (y >= 0) & (si >= 0).all(axis=1)
        ce = optax.sigmoid_binary_cross_entropy(
            logit, jnp.clip(y, 0, 1).astype(jnp.float32))
        return jnp.where(valid, ce, 0).sum() / jnp.maximum(valid.sum(), 1)

    x0 = jnp.zeros((sampler.node_capacity, feat.shape[1]), jnp.float32)
    ecap = sampler.node_capacity * 16
    params = model.init({"params": jax.random.PRNGKey(0)}, x0,
                        jnp.full((2, ecap), -1, jnp.int32),
                        jnp.zeros((ecap,), bool))
    opt_state = tx.init(params)
    step = make_scanned_subgraph_train_step(model, tx, sampler, feat,
                                            loss_fn, max_degree=16)

    m2 = labels.shape[0]
    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        order = rng.permutation(m2)
        losses, nbs, nb = [], [], 0
        per_block = bs * G
        for lo in range(0, m2, per_block):
            sel = order[lo: lo + per_block]
            sb = np.full((G, seed_width), -1, np.int64)
            yb = np.full((G, bs), -1, np.int64)
            pairs = links.T[sel]                      # [k, 2]
            k = pairs.shape[0]
            sb.reshape(-1)[: k * 2] = pairs.reshape(-1)
            yb.reshape(-1)[:k] = labels[sel]
            params, opt_state, ls = step(
                params, opt_state, sb, yb,
                jax.random.fold_in(jax.random.PRNGKey(epoch), lo))
            # Whole [G] blocks; one concat + one fetch below (see
            # glt_tpu.models.run_scanned_epoch).
            losses.append(ls)
            nbs.append(-(-k // bs))
            nb += -(-k // bs)
        flat = np.asarray(jax.device_get(jnp.concatenate(losses)))
        valid = np.concatenate(
            [np.arange(b) + i * G for i, b in enumerate(nbs)])
        mean = float(np.mean(flat[valid]))
        print(f"epoch {epoch}: loss={mean:.4f} "
              f"time={time.perf_counter() - t0:.2f}s")


if __name__ == "__main__":
    main()
