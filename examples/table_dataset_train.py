"""Train from table-format storage (the PAI/ODPS ingestion path).

TPU rebuild of the reference's ``examples/pai`` scripts: graph edges and
node features arrive as table records — ``(src, dst)`` rows for edges,
``(id, "f1:f2:...:fd")`` rows for nodes, label as the last feature column
— through a ``common_io``-compatible reader.  On PAI the reader factory
defaults to ``common_io.table.TableReader``; anywhere else any object
with ``read(batch_size, allow_smaller_final_batch=True)`` + ``close()``
works (here: an in-memory reader over synthetic records).

    python examples/table_dataset_train.py
"""
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np


class ListTableReader:
    """Minimal common_io-shaped reader over in-memory records."""

    def __init__(self, records):
        self._records = list(records)
        self._pos = 0

    def read(self, batch_size, allow_smaller_final_batch=True):
        if self._pos >= len(self._records):
            raise StopIteration
        got = self._records[self._pos: self._pos + batch_size]
        self._pos += len(got)
        return got

    def close(self):
        pass


def synthetic_tables(n=2000, deg=8, classes=6, seed=0):
    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(n), deg)
    dst = rng.integers(0, n, n * deg)
    labels = rng.integers(0, classes, n)
    feats = (np.eye(classes)[labels]
             + rng.normal(0, .3, (n, classes))).astype(np.float32)
    edge_records = list(zip(src.tolist(), dst.tolist()))
    node_records = [
        (i, ":".join(f"{v:.5f}" for v in feats[i]) + f":{labels[i]}")
        for i in range(n)]
    return {"edges": edge_records, "nodes": node_records}, classes


def main():
    import jax
    import optax

    from glt_tpu.data.table_dataset import TableDataset
    from glt_tpu.loader import NeighborLoader
    from glt_tpu.models import (GraphSAGE, create_train_state,
                                make_train_step)

    tables, classes = synthetic_tables()
    ds = TableDataset.from_tables(
        {"edge": "edges"}, {"node": "nodes"},
        reader_factory=lambda name: ListTableReader(tables[name]),
        graph_mode="DEVICE", label_from_last_column=True,
        reader_batch_size=256)
    n = ds.get_graph().num_nodes
    print(f"loaded from tables: {n} nodes, "
          f"{ds.get_graph().topo.num_edges} edges")

    bs = 128
    loader = NeighborLoader(ds, [5, 5], np.arange(n), batch_size=bs,
                            shuffle=True, seed=0)
    model = GraphSAGE(hidden_features=64, out_features=classes)
    first = next(iter(loader))
    tx = optax.adam(5e-3)
    state = create_train_state(model, jax.random.PRNGKey(0), first, tx)
    step = make_train_step(model, tx, batch_size=bs)
    for epoch in range(3):
        t0 = time.time()
        tot_l = tot_a = nb = 0
        for batch in loader:
            state, loss, acc = step(state, batch)
            tot_l += float(loss); tot_a += float(acc); nb += 1
        print(f"epoch {epoch}: loss {tot_l/nb:.4f} acc {tot_a/nb:.4f} "
              f"({time.time()-t0:.2f}s)")


if __name__ == "__main__":
    main()
