"""Feature store shared across processes, zero-copy.

TPU rebuild of the reference's ``examples/feature_mp.py``: there, a
``Feature`` built from CUDA-IPC handles is passed to spawned workers that
gather rows device-side.  On a TPU host the sharable tier is host DRAM:
``share_dataset`` puts the graph + feature pages in POSIX shared memory
once, workers ``attach_dataset`` and gather from the same physical pages
— no per-worker copy of a papers100M-scale feature matrix.

    python examples/feature_mp.py
"""
import multiprocessing as mp
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np


def build():
    from glt_tpu.data import Dataset

    rng = np.random.default_rng(0)
    n, deg, dim = 5000, 8, 64
    src = np.repeat(np.arange(n), deg)
    dst = rng.integers(0, n, n * deg)
    feat = np.arange(n, dtype=np.float32)[:, None] * np.ones(
        (1, dim), np.float32)
    return (Dataset()
            .init_graph(np.stack([src, dst]), graph_mode="HOST",
                        num_nodes=n)
            .init_node_features(feat))


def worker(rank, handle, q):
    from glt_tpu.data import attach_dataset

    ds = attach_dataset(handle)          # maps, doesn't copy
    ids = np.arange(rank * 100, rank * 100 + 50)
    rows = np.asarray(ds.get_node_feature().gather(ids))
    ok = bool((rows[:, 0] == ids).all())
    q.put((rank, ok, float(rows.sum())))


def main():
    from glt_tpu.data import share_dataset

    ds = build()
    handle = share_dataset(ds)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=worker, args=(r, handle, q))
             for r in range(3)]
    for p in procs:
        p.start()
    for _ in procs:
        rank, ok, s = q.get()
        print(f"worker {rank}: gather-correct={ok} checksum={s:.0f}")
        assert ok
    for p in procs:
        p.join()
    handle.unlink()
    print("feature_mp: 3 workers gathered from one shared copy")


if __name__ == "__main__":
    main()
