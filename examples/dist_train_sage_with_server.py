"""Supervised GraphSAGE trained through the server-client deployment.

TPU rebuild of the reference's
``examples/distributed/dist_train_sage_supervised_with_server.py``: the
sampling fleet runs on dedicated *server* processes (which own the graph
+ features and stream sampled batches over sockets); *client* trainer
processes hold only the model and consume ``RemoteNeighborLoader``.  The
reference separates the roles so graph storage and sampling CPUs scale
independently of the training accelerators — identical motivation here:
the TPU host keeps its chip on the train step while sampling servers run
anywhere.

Demo topology (single machine): N_SERVERS server processes x 1 trainer
client per server, spawned with multiprocessing.

    python examples/dist_train_sage_with_server.py --servers 2 --epochs 3
"""
import argparse
import multiprocessing as mp
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np


def build_dataset(scale: float = 0.02):
    from examples.datasets import synthetic_products

    ds, _ = synthetic_products(scale=scale, graph_mode="HOST")
    return ds


def server_proc(scale, conn):
    """Server role: owns the dataset, streams sampled batches."""
    from glt_tpu.distributed.dist_server import init_server

    srv = init_server(build_dataset(scale), dataset_builder=build_dataset,
                      builder_args=(scale,))
    conn.send(srv.addr)
    conn.recv()           # blocks until the trainer says shutdown
    srv.shutdown()


def trainer_proc(rank, world, addr, scale, epochs, batch_size,
                 num_workers=0):
    """Client role: remote loader + jitted train step, no local graph."""
    import jax
    import optax

    from examples.datasets import synthetic_products
    from glt_tpu.distributed import RemoteSamplingWorkerOptions
    from glt_tpu.distributed.dist_client import RemoteNeighborLoader
    from glt_tpu.distributed.dist_context import init_client_context
    from glt_tpu.models import (GraphSAGE, create_train_state,
                                make_train_step)

    init_client_context(num_clients=world, client_rank=rank,
                        num_servers=world)
    # Per-rank disjoint seed split (the reference splits train_idx across
    # trainer ranks, dist_train_sage_supervised.py:76).
    _, train_idx = synthetic_products(scale=scale, graph_mode="HOST")
    classes = 47  # ogbn-products label space
    seeds = train_idx[rank::world]
    # num_workers=0 keeps the demo to one sampling thread per server —
    # right-sized for a small host; raise it on real server machines.
    loader = RemoteNeighborLoader(
        addr, [15, 10, 5], seeds, batch_size=batch_size,
        worker_options=RemoteSamplingWorkerOptions(
            num_workers=num_workers, buffer_capacity=8, prefetch_size=4,
            channel_capacity_bytes=64 << 20))
    try:
        model = GraphSAGE(hidden_features=128, out_features=classes)
        first = next(iter(loader))
        tx = optax.adam(1e-3)
        state = create_train_state(model, jax.random.PRNGKey(0), first, tx)
        step = make_train_step(model, tx, batch_size=batch_size)
        for epoch in range(epochs):
            t0 = time.time()
            tot_l = tot_a = nb = 0
            for batch in loader:
                state, loss, acc = step(state, batch)
                tot_l += float(loss); tot_a += float(acc); nb += 1
            print(f"[client {rank}] epoch {epoch}: loss {tot_l/nb:.4f} "
                  f"acc {tot_a/nb:.4f} ({time.time()-t0:.2f}s)")
    finally:
        loader.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--servers", type=int, default=2)
    ap.add_argument("--scale", type=float, default=0.02)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--workers", type=int, default=0,
                    help="mp sampling workers per server producer")
    args = ap.parse_args()

    ctx = mp.get_context("spawn")
    servers, pipes = [], []
    for _ in range(args.servers):
        parent, child = ctx.Pipe()
        p = ctx.Process(target=server_proc, args=(args.scale, child))
        p.start()
        servers.append(p)
        pipes.append(parent)
    addrs = [pipe.recv() for pipe in pipes]
    print(f"servers up at {addrs}")

    trainers = [ctx.Process(target=trainer_proc,
                            args=(r, args.servers, addrs[r], args.scale,
                                  args.epochs, args.batch_size,
                                  args.workers))
                for r in range(args.servers)]
    for t in trainers:
        t.start()
    for t in trainers:
        t.join()
    for pipe in pipes:
        pipe.send("shutdown")
    for p in servers:
        p.join(timeout=15)
        if p.is_alive():
            p.terminate()
    print("done")


if __name__ == "__main__":
    main()
