"""R-GAT on a (synthetic) IGBH-shaped heterogeneous graph.

TPU rebuild of the reference's examples/igbh R-GAT training: hetero
neighbor sampling over paper/author/institute types, HeteroConv R-GAT,
paper-node classification.
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.datasets import synthetic_igbh
from glt_tpu.loader.hetero_neighbor_loader import HeteroNeighborLoader
from glt_tpu.models.rgat import RGAT
from glt_tpu.typing import reverse_edge_type


def run_distributed(args):
    """Multi-chip IGBH (BASELINE config 4): per-edge-type sharded CSRs,
    multi-type exchange sampling, fused R-GAT step over a device mesh
    (cf. the reference's examples/igbh distributed R-GAT).

    Run on a dev box:
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        JAX_PLATFORMS=cpu python examples/rgat_igbh.py --distributed 8
    """
    from jax.sharding import Mesh

    from glt_tpu.parallel import (
        DistHeteroNeighborSampler,
        init_hetero_dist_state,
        make_hetero_dist_train_step,
        shard_feature,
        shard_hetero_graph,
    )

    from examples.datasets import ensure_cpu_devices

    n_dev = args.distributed
    devices = ensure_cpu_devices(n_dev)
    if len(devices) < n_dev:
        raise RuntimeError(
            f"need {n_dev} devices, found {len(devices)}; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_dev} "
            f"JAX_PLATFORMS=cpu for the virtual CPU mesh")
    mesh = Mesh(np.array(devices[:n_dev]), ("shard",))

    ds, train_idx, classes = synthetic_igbh(scale=args.scale, use_real=args.use_real)
    topos = {et: g.topo for et, g in ds.graph.items()}
    sharded = shard_hetero_graph(topos, n_dev)
    feats = {t: shard_feature(np.asarray(ds.node_features[t]._host_full),
                              n_dev)
             for t in ds.get_node_types()}
    labels = np.asarray(ds.node_labels["paper"])
    per = sharded[("paper", "cites", "paper")].nodes_per_shard
    lab = jnp.asarray(np.pad(labels, (0, n_dev * per - labels.shape[0]),
                             constant_values=-1).reshape(n_dev, per))

    # Per-shard seed pools bound the usable batch size.
    owned = [train_idx[(train_idx // per) == s] for s in range(n_dev)]
    if min(len(o) for o in owned) == 0:
        raise RuntimeError(
            f"{n_dev} shards over {len(train_idx)} paper seeds leaves at "
            f"least one shard without any seeds; use fewer devices or a "
            f"larger --scale")
    bs = min(args.batch_size, min(len(o) for o in owned))
    sampler = DistHeteroNeighborSampler(sharded, mesh, [4, 4], "paper",
                                        batch_size=bs, frontier_cap=512,
                                        seed=0)
    batch_ets = [reverse_edge_type(et) for et in ds.get_edge_types()]
    model = RGAT(edge_types=batch_ets, hidden_features=32,
                 out_features=classes, target_type="paper", num_layers=2,
                 conv="gat", dropout_rate=0.0)
    tx = optax.adam(5e-3)
    state = init_hetero_dist_state(model, tx, sampler, feats,
                                   jax.random.PRNGKey(0))
    step = make_hetero_dist_train_step(model, tx, sampler, feats, lab,
                                       mesh, batch_size=bs)

    steps_per_epoch = max(min(len(o) for o in owned) // bs, 1)
    for epoch in range(args.epochs):
        rngs = [np.random.default_rng(1000 * epoch + s) for s in range(n_dev)]
        t0 = time.perf_counter()
        losses, accs = [], []
        for it in range(steps_per_epoch):
            seeds = np.stack([rngs[s].choice(owned[s], bs, replace=False)
                              for s in range(n_dev)]).astype(np.int32)
            state, loss, acc = step(state, jnp.asarray(seeds),
                                    jax.random.PRNGKey(epoch * 1000 + it))
            losses.append(loss)
            accs.append(acc)
        jax.device_get(losses[-1])
        dt = time.perf_counter() - t0  # before the summary fetches below
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"acc={float(np.mean(jax.device_get(accs))):.4f} "
              f"time={dt:.2f}s")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--use-real", action="store_true",
                    help="load the converted real IGBH from DATA_ROOT/"
                         "igbh-tiny instead of the synthetic fixture")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--distributed", type=int, default=0, metavar="N",
                    help="train on an N-device mesh (0 = single device)")
    # G-batch scan (DEFAULT): one program trains --group consecutive
    # hetero batches — config-4's eager loader loop is dispatch-bound
    # (~60 ms/batch pure overhead on the tunnel); equivalence tested in
    # tests/test_hetero.py::test_scanned_hetero_step_matches_eager.
    ap.add_argument("--group", type=int, default=8,
                    help="scan G batches per program (0 = eager loader)")
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--data-root", default=None,
                    help="dir holding a converted IGBH "
                         "(scripts/convert_ogb.py igbh); overrides "
                         "GLT_DATA_ROOT")
    args = ap.parse_args()
    if args.data_root:
        import examples.datasets as _exds

        _exds.DATA_ROOT = args.data_root

    if args.distributed:
        return run_distributed(args)

    ds, train_idx, classes = synthetic_igbh(scale=args.scale, use_real=args.use_real)

    batch_ets = [reverse_edge_type(et) for et in ds.get_edge_types()]
    model = RGAT(edge_types=batch_ets, hidden_features=32,
                 out_features=classes, target_type="paper", num_layers=2,
                 conv="gat", dropout_rate=0.0,
                 dtype=jax.numpy.bfloat16 if args.bf16 else None)

    if args.group > 0:
        from glt_tpu.models import (
            init_hetero_state,
            make_scanned_hetero_train_step,
            run_scanned_epoch,
        )
        from glt_tpu.sampler.hetero_neighbor_sampler import (
            HeteroNeighborSampler,
        )

        sampler = HeteroNeighborSampler(ds.graph, [4, 4], "paper",
                                        batch_size=args.batch_size,
                                        seed=0)
        feats = {t: ds.get_node_feature(t)
                 for t in ds.get_node_types()}
        labels = {"paper": np.asarray(ds.node_labels["paper"])}
        tx = optax.adam(5e-3)
        state = init_hetero_state(model, tx, sampler, feats,
                                  jax.random.PRNGKey(0))
        sstep = make_scanned_hetero_train_step(
            model, tx, sampler, feats, labels, args.batch_size)
        rng = np.random.default_rng(0)
        for epoch in range(args.epochs):
            t0 = time.perf_counter()
            state, losses, accs, _ = run_scanned_epoch(
                sstep, state, train_idx, args.batch_size, args.group,
                rng, jax.random.PRNGKey(100 + epoch))
            dt = time.perf_counter() - t0
            print(f"epoch {epoch}: loss={float(np.mean(losses)):.4f} "
                  f"acc={float(np.mean(accs)):.4f} time={dt:.2f}s")
        return

    loader = HeteroNeighborLoader(ds, [4, 4], ("paper", train_idx),
                                  batch_size=args.batch_size, shuffle=True)

    first = next(iter(loader))
    params = model.init({"params": jax.random.PRNGKey(0)}, first.x,
                        first.edge_index, first.edge_mask)
    tx = optax.adam(5e-3)
    opt_state = tx.init(params)
    bs = args.batch_size

    @jax.jit
    def step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply(p, batch.x, batch.edge_index,
                                 batch.edge_mask)
            y = batch.y["paper"][:bs]
            valid = y >= 0
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:bs], jnp.where(valid, y, 0))
            loss = jnp.where(valid, ce, 0).sum() / jnp.maximum(valid.sum(), 1)
            acc = jnp.where(valid, jnp.argmax(logits[:bs], -1) == y,
                            False).sum() / jnp.maximum(valid.sum(), 1)
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        losses, accs = [], []
        for batch in loader:
            params, opt_state, loss, acc = step(params, opt_state, batch)
            losses.append(loss)
            accs.append(acc)
        # device_get is a true sync; block_until_ready does not
        # wait under the axon tunnel (see bench.py docstring).
        jax.device_get(losses[-1])
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"acc={float(np.mean(jax.device_get(accs))):.4f} "
              f"time={time.perf_counter() - t0:.2f}s")


if __name__ == "__main__":
    main()
