"""R-GAT on a (synthetic) IGBH-shaped heterogeneous graph.

TPU rebuild of the reference's examples/igbh R-GAT training: hetero
neighbor sampling over paper/author/institute types, HeteroConv R-GAT,
paper-node classification.
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.datasets import synthetic_igbh
from glt_tpu.loader.hetero_neighbor_loader import HeteroNeighborLoader
from glt_tpu.models.rgat import RGAT
from glt_tpu.typing import reverse_edge_type


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    ds, train_idx, classes = synthetic_igbh(scale=args.scale)
    loader = HeteroNeighborLoader(ds, [4, 4], ("paper", train_idx),
                                  batch_size=args.batch_size, shuffle=True)

    batch_ets = [reverse_edge_type(et) for et in ds.get_edge_types()]
    model = RGAT(edge_types=batch_ets, hidden_features=32,
                 out_features=classes, target_type="paper", num_layers=2,
                 conv="gat", dropout_rate=0.0)

    first = next(iter(loader))
    params = model.init({"params": jax.random.PRNGKey(0)}, first.x,
                        first.edge_index, first.edge_mask)
    tx = optax.adam(5e-3)
    opt_state = tx.init(params)
    bs = args.batch_size

    @jax.jit
    def step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply(p, batch.x, batch.edge_index,
                                 batch.edge_mask)
            y = batch.y["paper"][:bs]
            valid = y >= 0
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:bs], jnp.where(valid, y, 0))
            loss = jnp.where(valid, ce, 0).sum() / jnp.maximum(valid.sum(), 1)
            acc = jnp.where(valid, jnp.argmax(logits[:bs], -1) == y,
                            False).sum() / jnp.maximum(valid.sum(), 1)
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        losses, accs = [], []
        for batch in loader:
            params, opt_state, loss, acc = step(params, opt_state, batch)
            losses.append(loss)
            accs.append(acc)
        # device_get is a true sync; block_until_ready does not
        # wait under the axon tunnel (see bench.py docstring).
        jax.device_get(losses[-1])
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"acc={float(np.mean(jax.device_get(accs))):.4f} "
              f"time={time.perf_counter() - t0:.2f}s")


if __name__ == "__main__":
    main()
