"""HGT on an OGB-MAG-shaped heterogeneous graph.

TPU rebuild of the reference's ``examples/hetero/train_hgt_mag.py``:
hetero neighbor sampling over MAG's paper/author/institution/field types,
a flax Heterogeneous Graph Transformer (``glt_tpu/models/hgt.py``), paper
venue classification.  One fused XLA program per train step.
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from examples.datasets import synthetic_mag
from glt_tpu.loader.hetero_neighbor_loader import HeteroNeighborLoader
from glt_tpu.models import HGT
from glt_tpu.typing import reverse_edge_type


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--fanout", type=int, nargs="+", default=[5, 5])
    ap.add_argument("--last-hop-dedup", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="exact final-hop dedup (default); "
                         "--no-last-hop-dedup opts into the fast leaf block")
    # G-batch scan: one program trains --group consecutive hetero
    # batches (see rgat_igbh.py — per-batch dispatch dominates small
    # hetero batches on TPU).  0 = eager loader loop.
    ap.add_argument("--group", type=int, default=8)
    ap.add_argument("--bf16", action="store_true")
    args = ap.parse_args()

    ds, train_idx, classes = synthetic_mag(scale=args.scale)
    batch_ets = sorted(reverse_edge_type(et) for et in ds.graph)

    model = HGT(edge_types=batch_ets, hidden_features=args.hidden,
                out_features=classes, target_type="paper",
                num_layers=len(args.fanout), heads=args.heads,
                dropout_rate=0.3,
                dtype=jnp.bfloat16 if args.bf16 else None)

    if args.group > 0:
        from glt_tpu.models import (
            init_hetero_state,
            make_scanned_hetero_train_step,
            run_scanned_epoch,
        )
        from glt_tpu.sampler.hetero_neighbor_sampler import (
            HeteroNeighborSampler,
        )

        sampler = HeteroNeighborSampler(
            ds.graph, args.fanout, "paper", batch_size=args.batch_size,
            seed=0, last_hop_dedup=args.last_hop_dedup)
        feats = {t: ds.get_node_feature(t) for t in ds.get_node_types()}
        labels = {"paper": np.asarray(ds.node_labels["paper"])}
        tx = optax.adam(1e-3)
        state = init_hetero_state(model, tx, sampler, feats,
                                  jax.random.PRNGKey(0))
        sstep = make_scanned_hetero_train_step(
            model, tx, sampler, feats, labels, args.batch_size)
        rng = np.random.default_rng(0)
        for epoch in range(args.epochs):
            t0 = time.perf_counter()
            state, losses, accs, _ = run_scanned_epoch(
                sstep, state, train_idx, args.batch_size, args.group,
                rng, jax.random.PRNGKey(100 + epoch))
            dt = time.perf_counter() - t0
            print(f"epoch {epoch}: loss {float(np.mean(losses)):.4f} "
                  f"acc {float(np.mean(accs)):.4f} "
                  f"({dt:.2f}s, {len(losses)} batches)")
        return

    loader = HeteroNeighborLoader(
        ds, args.fanout, ("paper", train_idx),
        batch_size=args.batch_size, shuffle=True, seed=0,
        last_hop_dedup=args.last_hop_dedup)
    first = next(iter(loader))
    tx = optax.adam(1e-3)
    params = model.init({"params": jax.random.PRNGKey(0)}, first.x,
                        first.edge_index, first.edge_mask)
    opt_state = tx.init(params)
    bsz = args.batch_size

    @jax.jit
    def step(params, opt_state, batch, rng):
        def loss_fn(p):
            logits = model.apply(p, batch.x, batch.edge_index,
                                 batch.edge_mask, train=True,
                                 rngs={"dropout": rng})
            y = batch.y["paper"][:bsz]
            valid = y >= 0
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:bsz], jnp.where(valid, y, 0))
            loss = jnp.where(valid, ce, 0).sum() / jnp.maximum(valid.sum(), 1)
            acc = jnp.where(valid, logits[:bsz].argmax(-1) == y,
                            False).sum() / jnp.maximum(valid.sum(), 1)
            return loss, acc
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    rng = jax.random.PRNGKey(1)
    for epoch in range(args.epochs):
        t0 = time.time()
        tot_l = tot_a = nb = 0
        for batch in loader:
            rng, sub = jax.random.split(rng)
            params, opt_state, loss, acc = step(params, opt_state, batch, sub)
            tot_l += float(loss); tot_a += float(acc); nb += 1
        print(f"epoch {epoch}: loss {tot_l/nb:.4f} acc {tot_a/nb:.4f} "
              f"({time.time()-t0:.2f}s, {nb} batches)")


if __name__ == "__main__":
    main()
