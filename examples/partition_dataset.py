"""Offline dataset partitioning CLI.

TPU rebuild of the reference's
``examples/distributed/partition_ogbn_dataset.py``: partition a graph +
features into the on-disk layout ``DistDataset.load`` consumes
(``META.json`` + ``node_pb``/``edge_pb`` + ``part{i}/graph|node_feat``),
with either uniform random assignment or the hotness-aware frequency
partitioner (per-trainer access probabilities from
``NeighborSampler.sample_prob``, the ``CalNbrProb`` pipeline).

    python examples/partition_dataset.py --out /tmp/parts --num-parts 4
    python examples/partition_dataset.py --out /tmp/parts --num-parts 4 \\
        --partitioner frequency --cache-ratio 0.1
"""
import argparse
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--num-parts", type=int, default=4)
    ap.add_argument("--scale", type=float, default=0.01,
                    help="synthetic ogbn-products scale (real data loads "
                         "from disk when present; see examples/datasets.py)")
    ap.add_argument("--partitioner", choices=["random", "frequency"],
                    default="random")
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    ap.add_argument("--cache-ratio", type=float, default=0.1,
                    help="hot-cache fraction per partition (frequency)")
    ap.add_argument("--chunk-size", type=int, default=0,
                    help="greedy-assignment granularity; 0 = adaptive "
                         "(>=20 chunks per partition)")
    args = ap.parse_args()

    from examples.datasets import synthetic_products
    from glt_tpu.partition import FrequencyPartitioner, RandomPartitioner

    ds, train_idx = synthetic_products(scale=args.scale, graph_mode="HOST")
    topo = ds.get_graph().topo
    n = topo.num_nodes
    feat = np.asarray(ds.node_features._host_full)
    edge_index = np.stack(topo.to_coo())
    chunk = args.chunk_size or min(10000, max(n // (20 * args.num_parts), 1))
    print(f"partitioning {n} nodes / {topo.num_edges} edges "
          f"into {args.num_parts} parts ({args.partitioner})")

    if args.partitioner == "random":
        part = RandomPartitioner(args.out, args.num_parts, n, edge_index,
                                 node_feat=feat,
                                 chunk_size=chunk)
    else:
        # Per-trainer hotness: each rank's seed slice drives sample_prob
        # (cf. partition_ogbn_dataset.py + neighbor_sampler.py:435-562).
        from glt_tpu.sampler import NeighborSampler

        sampler = NeighborSampler(ds.get_graph(), args.fanout,
                                  batch_size=1024)
        probs = [
            np.asarray(sampler.sample_prob(
                train_idx[r::args.num_parts], n))
            for r in range(args.num_parts)]
        part = FrequencyPartitioner(args.out, args.num_parts, n, edge_index,
                                    probs=probs, node_feat=feat,
                                    cache_ratio=args.cache_ratio,
                                    chunk_size=chunk)
    part.partition()
    print(f"wrote partition layout to {args.out}")

    from glt_tpu.partition import load_partition
    graph, node_feat, _, node_pb, edge_pb, meta = load_partition(args.out, 0)
    print(f"verified part0: {node_feat.ids.shape[0]} owned feature rows, "
          f"{graph.eids.shape[0]} edges, meta={meta}")


if __name__ == "__main__":
    main()
