"""Supervised GraphSAGE on a REAL dataset: the sklearn digits k-NN graph.

Config-1's EXACT pipeline (the code path of train_sage_products.py —
NeighborSampler, occupancy auto-cap, bf16 matmuls, fused scanned-epoch
train step) on real features/labels: 1797 handwritten-digit images, 64 raw
pixel features, 10 classes, symmetric 8-NN graph
(scripts/make_digits_graph.py; the data ships in-repo under
data/digits-knn).  Reports held-out test accuracy against the non-graph
baselines recorded in the dataset's META.json (k-NN ~0.975, logistic
regression ~0.958 on the same split).

    python examples/train_sage_digits.py --epochs 30

A user with a converted real ogbn-products runs the identical pipeline
via examples/train_sage_products.py --data-root <dir> instead.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
import optax

import examples.datasets as exds
from glt_tpu.loader import NeighborLoader
from glt_tpu.models import (
    GraphSAGE,
    TrainState,
    make_eval_step,
    make_scanned_node_train_step,
    run_scanned_epoch,
)
from glt_tpu.sampler import NeighborSampler, calibrate_node_capacity
from examples.train_sage_products import seed_batches


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                    default=True)
    ap.add_argument("--group", type=int, default=4,
                    help="batches per fused scan-group program")
    ap.add_argument("--auto-cap", action=argparse.BooleanOptionalAction,
                    default=True)
    ap.add_argument("--data-root", default=None)
    args = ap.parse_args()
    if args.data_root:
        exds.DATA_ROOT = args.data_root
    elif not os.path.isdir(os.path.join(exds.DATA_ROOT, "digits-knn")):
        # The in-repo copy (the default for this example).
        exds.DATA_ROOT = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "data")

    loaded = exds._from_disk("digits-knn", graph_mode="DEVICE")
    if loaded is None:
        raise FileNotFoundError(
            "data/digits-knn missing — run scripts/make_digits_graph.py")
    ds, train_idx = loaded
    root = os.path.join(exds.DATA_ROOT, "digits-knn")
    test_idx = np.load(os.path.join(root, "test_idx.npy"))
    with open(os.path.join(root, "META.json")) as fh:
        meta = json.load(fh)
    classes = int(np.asarray(ds.get_node_label()).max()) + 1

    model = GraphSAGE(hidden_features=args.hidden, out_features=classes,
                      num_layers=len(args.fanout),
                      dtype=jax.numpy.bfloat16 if args.bf16 else None)
    tx = optax.adam(args.lr)

    node_cap = None
    if args.auto_cap:
        probe = NeighborSampler(ds.get_graph(), args.fanout,
                                batch_size=args.batch_size, with_edge=False)
        rng_cal = np.random.default_rng(42)
        cal = [b for b, _ in zip(
            seed_batches(train_idx, args.batch_size, rng_cal), range(6))]
        node_cap = calibrate_node_capacity(probe, cal)
        print(f"auto-cap: node_capacity {node_cap} "
              f"({node_cap / probe.full_node_capacity:.0%} of worst case)")

    sampler = NeighborSampler(ds.get_graph(), args.fanout,
                              batch_size=args.batch_size, with_edge=False,
                              node_capacity=node_cap)
    feat = ds.get_node_feature()
    labels = np.asarray(ds.get_node_label())
    x0 = jax.numpy.zeros((sampler.node_capacity, feat.shape[1]), feat.dtype)
    ei0 = jax.numpy.full((2, sampler.edge_capacity), -1, jax.numpy.int32)
    m0 = jax.numpy.zeros((sampler.edge_capacity,), bool)
    params = model.init({"params": jax.random.PRNGKey(0)}, x0, ei0, m0)
    state = TrainState(params=params, opt_state=tx.init(params),
                       step=jax.numpy.zeros((), jax.numpy.int32))
    # The fused scanned epoch (the only compiled epoch driver): G
    # consecutive sample->gather->train batches per XLA program.
    step = make_scanned_node_train_step(
        model, tx, sampler, feat, labels, args.batch_size)
    rng = np.random.default_rng(0)

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        state, losses, accs, _ovf = run_scanned_epoch(
            step, state, train_idx, args.batch_size, args.group, rng,
            jax.random.PRNGKey(100 + epoch))
        dt = time.perf_counter() - t0
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            print(f"epoch {epoch}: "
                  f"loss={float(np.mean(losses)):.4f} "
                  f"train_acc={float(np.mean(accs)):.4f} "
                  f"time={dt:.2f}s")

    # Held-out accuracy through the SAME sampling pipeline (eval mode).
    ev = make_eval_step(model, batch_size=args.batch_size)
    loader = NeighborLoader(ds, args.fanout, test_idx,
                            batch_size=args.batch_size, sampler=sampler)
    accs, weights = [], []
    for b in loader:
        _, acc = ev(state.params, b)
        accs.append(float(acc))
        weights.append(b.batch_size)   # valid seeds (trailing batch < bs)
    test_acc = float(np.average(accs, weights=weights))
    base = meta.get("baseline_acc", {})
    print(f"TEST accuracy: {test_acc:.4f}  "
          f"(baselines on same split: {base})")
    return test_acc


if __name__ == "__main__":
    main()
