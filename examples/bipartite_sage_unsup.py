"""Unsupervised bipartite GraphSAGE on a user-item graph.

TPU rebuild of the reference's ``examples/hetero/bipartite_sage_unsup.py``:
hetero link-neighbor sampling over the ``user -> item`` seed edge type with
binary negatives, two-tower hetero SAGE encoders, a dot-product edge
decoder, BCE on ``edge_label`` — each train step one fused XLA program.
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from glt_tpu.data import Dataset
from glt_tpu.loader.hetero_link_loader import HeteroLinkNeighborLoader
from glt_tpu.models.rgat import HeteroConv
from glt_tpu.sampler import NegativeSampling
from glt_tpu.typing import reverse_edge_type

ET_UI = ("user", "clicks", "item")
ET_IU = ("item", "rev_clicks", "user")


def synthetic_user_item(n_users=600, n_items=300, deg=6, seed=0):
    """Users click items near ``u % n_items`` — structure recoverable
    from the graph alone (the unsupervised task)."""
    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(n_users), deg)
    dst = (src % n_items + rng.integers(0, 8, src.shape[0])) % n_items
    ei = {ET_UI: np.stack([src, dst]), ET_IU: np.stack([dst, src])}
    feats = {
        "user": rng.normal(size=(n_users, 16)).astype(np.float32),
        "item": rng.normal(size=(n_items, 16)).astype(np.float32),
    }
    ds = (Dataset()
          .init_graph(ei, graph_mode="DEVICE",
                      num_nodes={"user": n_users, "item": n_items})
          .init_node_features(feats))
    return ds, np.stack([src, dst])


class TwoTowerSAGE(nn.Module):
    """Per-type hetero SAGE encoders + dot-product edge decoder
    (cf. ItemGNNEncoder/UserGNNEncoder/EdgeDecoder in the reference)."""
    edge_types: tuple
    hidden: int = 64
    out: int = 32
    num_layers: int = 2

    @nn.compact
    def __call__(self, x, edge_index, edge_mask, edge_label_index):
        h = {t: nn.Dense(self.hidden, name=f"in_{t}")(v)
             for t, v in x.items()}
        for i in range(self.num_layers):
            out = HeteroConv(self.edge_types, self.hidden, conv="sage",
                             name=f"layer{i}")(h, edge_index, edge_mask)
            h = {t: nn.relu(out[t]) if t in out else h[t] for t in h}
        z = {t: nn.Dense(self.out, name=f"out_{t}")(v)
             for t, v in h.items()}
        zu = z["user"][jnp.clip(edge_label_index[0], 0, None)]
        zi = z["item"][jnp.clip(edge_label_index[1], 0, None)]
        return (zu * zi).sum(-1)      # [Q] logits


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--fanout", type=int, nargs="+", default=[8, 4])
    args = ap.parse_args()

    ds, pos_edges = synthetic_user_item()
    loader = HeteroLinkNeighborLoader(
        ds, args.fanout, (ET_UI, pos_edges),
        neg_sampling=NegativeSampling("binary", 1.0),
        batch_size=args.batch_size, shuffle=True, seed=0)
    batch_ets = sorted(reverse_edge_type(et) for et in ds.graph)
    model = TwoTowerSAGE(edge_types=tuple(batch_ets))

    first = next(iter(loader))
    eli0 = first.metadata["edge_label_index"]
    params = model.init(jax.random.PRNGKey(0), first.x, first.edge_index,
                        first.edge_mask, eli0)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        eli = batch.metadata["edge_label_index"]
        label = batch.metadata["edge_label"]

        def loss_fn(p):
            logits = model.apply(p, batch.x, batch.edge_index,
                                 batch.edge_mask, eli)
            valid = label >= 0
            y = jnp.clip(label, 0, 1).astype(jnp.float32)
            bce = optax.sigmoid_binary_cross_entropy(logits, y)
            loss = jnp.where(valid, bce, 0).sum() / jnp.maximum(
                valid.sum(), 1)
            acc = jnp.where(valid, (logits > 0) == (y > 0.5),
                            False).sum() / jnp.maximum(valid.sum(), 1)
            return loss, acc
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    for epoch in range(args.epochs):
        t0 = time.time()
        tot_l = tot_a = nb = 0
        for batch in loader:
            params, opt_state, loss, acc = step(params, opt_state, batch)
            tot_l += float(loss); tot_a += float(acc); nb += 1
        print(f"epoch {epoch}: bce {tot_l/nb:.4f} link-acc {tot_a/nb:.4f} "
              f"({time.time()-t0:.2f}s)")


if __name__ == "__main__":
    main()
