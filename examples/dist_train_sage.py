"""Distributed GraphSAGE over a device mesh — the papers100M-style config.

TPU rebuild of the reference's examples/distributed/dist_train_sage_supervised.py:
instead of per-machine partitions + RPC sampling workers + DDP, the graph
and features are sharded across a jax Mesh and the whole iteration
(all-to-all sampling, feature gather, fwd/bwd, grad pmean) is one jitted
program (glt_tpu.parallel.dist_train).

On a single-chip dev box run with virtual devices:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/dist_train_sage.py --devices 8 --scale 0.002
"""
import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--scale", type=float, default=0.002)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--fanout", type=int, nargs="+", default=[10, 5])
    ap.add_argument("--frontier-cap", type=int, default=1024)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from examples.datasets import synthetic_products
    from glt_tpu.models import GraphSAGE
    from glt_tpu.parallel import (
        init_dist_state,
        make_dist_train_step,
        shard_feature,
        shard_graph,
    )

    devices = jax.devices()[: args.devices]
    if len(devices) < args.devices:
        raise SystemExit(f"need {args.devices} devices, have {len(devices)}")
    mesh = Mesh(np.array(devices), ("shard",))

    ds, train_idx = synthetic_products(scale=args.scale, graph_mode="HOST")
    topo = ds.get_graph().topo
    feat = ds.get_node_feature()._host_full
    labels = np.asarray(ds.get_node_label())

    g = shard_graph(topo, args.devices)
    f = shard_feature(feat, args.devices)
    pad = args.devices * g.nodes_per_shard - labels.shape[0]
    lab = jnp.asarray(np.pad(labels, (0, pad), constant_values=-1)
                      .reshape(args.devices, g.nodes_per_shard))

    model = GraphSAGE(hidden_features=128, out_features=47,
                      num_layers=len(args.fanout), dropout_rate=0.0)
    tx = optax.adam(1e-3)
    state = init_dist_state(model, tx, g, f, jax.random.PRNGKey(0),
                            args.fanout, args.batch_size)
    step = make_dist_train_step(model, tx, g, f, lab, mesh, args.fanout,
                                args.batch_size,
                                frontier_cap=args.frontier_cap)

    # per-shard disjoint seed split (dist_train_sage_supervised.py:76)
    rng = np.random.default_rng(0)
    per_shard = [train_idx[train_idx // g.nodes_per_shard == s]
                 for s in range(args.devices)]
    steps_per_epoch = min(max(1, len(p) // args.batch_size)
                          for p in per_shard)

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        losses = []
        for it in range(steps_per_epoch):
            seeds = np.stack([
                rng.choice(p, args.batch_size,
                           replace=len(p) < args.batch_size)
                for p in per_shard]).astype(np.int32)
            state, loss, acc = step(state, jnp.asarray(seeds),
                                    jax.random.PRNGKey(epoch * 1000 + it))
            losses.append(loss)
        # device_get is a true sync; block_until_ready does not
        # wait under the axon tunnel (see bench.py docstring).
        jax.device_get(losses[-1])
        dt = time.perf_counter() - t0
        print(f"epoch {epoch}: loss={float(np.mean(jax.device_get(losses))):.4f} "
              f"time={dt:.2f}s "
              f"subgraphs/s={steps_per_epoch * args.devices / dt:.1f}")


if __name__ == "__main__":
    main()
