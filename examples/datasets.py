"""Example datasets: real OGB data when present on disk, synthetic otherwise.

The container has no network egress, so examples default to synthetic
graphs shaped like their real counterparts (node/edge counts scaled by
--scale).  Drop pre-downloaded OGB .npy files under DATA_ROOT to run the
real thing:

    DATA_ROOT/<name>/{indptr,indices,feat,labels,train_idx}.npy
"""
from __future__ import annotations

import os

import numpy as np

from glt_tpu.data import CSRTopo, Dataset

DATA_ROOT = os.environ.get("GLT_DATA_ROOT", "/root/data")


def ensure_cpu_devices(n: int):
    """Return >= n jax devices, falling back to the virtual CPU pool.

    Dev-box workaround: an ambient TPU plugin may have pinned platform
    selection at interpreter start, overriding JAX_PLATFORMS=cpu +
    xla_force_host_platform_device_count; re-point JAX at CPU and reset
    backends.  Shared by the distributed examples.
    """
    import jax

    devices = jax.devices()
    if len(devices) < n:
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
        devices = jax.devices()
    return devices


def _from_disk(name: str, graph_mode: str):
    root = os.path.join(DATA_ROOT, name)
    if not os.path.isdir(root):
        return None
    load = lambda f: np.load(os.path.join(root, f + ".npy"), mmap_mode="r")
    topo = CSRTopo((np.asarray(load("indptr")), np.asarray(load("indices"))),
                   layout="CSR")
    ds = Dataset()
    ds.graph = __import__("glt_tpu.data.graph", fromlist=["Graph"]).Graph(
        topo, mode=graph_mode)
    ds.init_node_features(np.asarray(load("feat")))
    ds.init_node_labels(np.asarray(load("labels")))
    return ds, np.asarray(load("train_idx"))


def synthetic_products(scale: float = 0.01, dim: int = 100,
                       num_classes: int = 47, graph_mode: str = "DEVICE",
                       seed: int = 0):
    """ogbn-products-shaped synthetic graph (2.45M nodes / 62M edges at
    scale=1.0) with learnable community structure."""
    real = _from_disk("ogbn-products", graph_mode)
    if real is not None:
        return real

    rng = np.random.default_rng(seed)
    n = max(1000, int(2_449_029 * scale))
    deg = 12
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    # Community-biased neighbors: ~70% same-class, rest uniform.
    indptr = (np.arange(n + 1) * deg).astype(np.int64)
    targets = rng.integers(0, n, (n, deg), dtype=np.int64)
    same_mask = rng.random((n, deg)) < 0.7
    # redirect same-class picks to a random member of the same class
    class_members = [np.flatnonzero(labels == c) for c in range(num_classes)]
    for c in range(num_classes):
        rows = np.flatnonzero(labels == c)
        picks = rng.choice(class_members[c], size=(rows.shape[0], deg))
        targets[rows] = np.where(same_mask[rows], picks, targets[rows])
    indices = targets.reshape(-1)

    feat = (np.eye(num_classes, dtype=np.float32)[labels]
            @ rng.normal(0, 1, (num_classes, dim)).astype(np.float32))
    feat += rng.normal(0, 0.5, (n, dim)).astype(np.float32)

    topo = CSRTopo((indptr.astype(np.int32), indices.astype(np.int32)),
                   layout="CSR")
    from glt_tpu.data.graph import Graph

    ds = Dataset(graph=Graph(topo, mode=graph_mode))
    ds.init_node_features(feat)
    ds.init_node_labels(labels)
    train_idx = rng.permutation(n)[: int(n * 0.1)]
    return ds, train_idx


def synthetic_ppi(scale: float = 1.0, dim: int = 50, seed: int = 0,
                  graph_mode: str = "DEVICE"):
    """PPI-shaped graph for unsupervised link prediction."""
    rng = np.random.default_rng(seed)
    n = max(500, int(14_755 * scale))
    deg = 14
    indptr = (np.arange(n + 1) * deg).astype(np.int64)
    indices = rng.integers(0, n, n * deg, dtype=np.int64)
    feat = rng.normal(size=(n, dim)).astype(np.float32)
    topo = CSRTopo((indptr.astype(np.int32), indices.astype(np.int32)),
                   layout="CSR")
    from glt_tpu.data.graph import Graph

    ds = Dataset(graph=Graph(topo, mode=graph_mode,
                             with_sorted_columns=True))
    ds.init_node_features(feat)
    src, dst = topo.to_coo()
    return ds, np.stack([src, dst])


def synthetic_igbh(scale: float = 1.0, seed: int = 0,
                   graph_mode: str = "DEVICE"):
    """IGBH-tiny-shaped hetero graph: paper/author/institute."""
    rng = np.random.default_rng(seed)
    n_paper = max(200, int(1000 * scale))
    n_author = max(150, int(800 * scale))
    n_inst = max(20, int(80 * scale))
    classes = 8

    def rand_edges(ns, nd, deg):
        src = np.repeat(np.arange(ns), deg)
        dst = rng.integers(0, nd, ns * deg)
        return np.stack([src, dst])

    cites = rand_edges(n_paper, n_paper, 4)
    writes = rand_edges(n_author, n_paper, 3)
    affil = rand_edges(n_author, n_inst, 1)
    ei = {
        ("paper", "cites", "paper"): cites,
        ("author", "writes", "paper"): writes,
        ("paper", "rev_writes", "author"): writes[::-1],
        ("author", "affiliated", "institute"): affil,
        ("institute", "rev_affiliated", "author"): affil[::-1],
    }
    labels = rng.integers(0, classes, n_paper).astype(np.int32)
    feats = {
        "paper": (np.eye(classes, dtype=np.float32)[labels]
                  + rng.normal(0, .3, (n_paper, classes)).astype(np.float32)),
        "author": rng.normal(size=(n_author, classes)).astype(np.float32),
        "institute": rng.normal(size=(n_inst, classes)).astype(np.float32),
    }
    ds = (Dataset()
          .init_graph(ei, graph_mode=graph_mode,
                      num_nodes={"paper": n_paper, "author": n_author,
                                 "institute": n_inst})
          .init_node_features(feats)
          .init_node_labels({"paper": labels}))
    return ds, np.arange(n_paper), classes
