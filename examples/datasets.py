"""Example datasets: real OGB data when present on disk, synthetic otherwise.

The container has no network egress, so examples default to synthetic
graphs shaped like their real counterparts (node/edge counts scaled by
--scale).  Drop pre-downloaded OGB .npy files under DATA_ROOT to run the
real thing:

    DATA_ROOT/<name>/{indptr,indices,feat,labels,train_idx}.npy
"""
from __future__ import annotations

import os

import numpy as np

from glt_tpu.data import CSRTopo, Dataset

DATA_ROOT = os.environ.get("GLT_DATA_ROOT", "/root/data")


def ensure_cpu_devices(n: int):
    """Return >= n jax devices, falling back to the virtual CPU pool.

    Dev-box workaround: an ambient TPU plugin may have pinned platform
    selection at interpreter start, overriding JAX_PLATFORMS=cpu +
    xla_force_host_platform_device_count; re-point JAX at CPU and reset
    backends.  Shared by the distributed examples.
    """
    import jax

    devices = jax.devices()
    if len(devices) < n:
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
        devices = jax.devices()
    return devices


def _from_disk(name: str, graph_mode: str):
    root = os.path.join(DATA_ROOT, name)
    if not os.path.isdir(root):
        return None
    load = lambda f: np.load(os.path.join(root, f + ".npy"), mmap_mode="r")
    topo = CSRTopo((np.asarray(load("indptr")), np.asarray(load("indices"))),
                   layout="CSR")
    ds = Dataset()
    ds.graph = __import__("glt_tpu.data.graph", fromlist=["Graph"]).Graph(
        topo, mode=graph_mode)
    ds.init_node_features(np.asarray(load("feat")))
    ds.init_node_labels(np.asarray(load("labels")))
    return ds, np.asarray(load("train_idx"))


def synthetic_products(scale: float = 0.01, dim: int = 100,
                       num_classes: int = 47, graph_mode: str = "DEVICE",
                       seed: int = 0):
    """ogbn-products-shaped synthetic graph (2.45M nodes / 62M edges at
    scale=1.0) with learnable community structure."""
    real = _from_disk("ogbn-products", graph_mode)
    if real is not None:
        return real

    rng = np.random.default_rng(seed)
    n = max(1000, int(2_449_029 * scale))
    deg = 12
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    # Community-biased neighbors: ~70% same-class, rest uniform.
    indptr = (np.arange(n + 1) * deg).astype(np.int64)
    targets = rng.integers(0, n, (n, deg), dtype=np.int64)
    same_mask = rng.random((n, deg)) < 0.7
    # redirect same-class picks to a random member of the same class
    class_members = [np.flatnonzero(labels == c) for c in range(num_classes)]
    for c in range(num_classes):
        rows = np.flatnonzero(labels == c)
        picks = rng.choice(class_members[c], size=(rows.shape[0], deg))
        targets[rows] = np.where(same_mask[rows], picks, targets[rows])
    indices = targets.reshape(-1)

    feat = (np.eye(num_classes, dtype=np.float32)[labels]
            @ rng.normal(0, 1, (num_classes, dim)).astype(np.float32))
    feat += rng.normal(0, 0.5, (n, dim)).astype(np.float32)

    topo = CSRTopo((indptr.astype(np.int32), indices.astype(np.int32)),
                   layout="CSR")
    from glt_tpu.data.graph import Graph

    ds = Dataset(graph=Graph(topo, mode=graph_mode))
    ds.init_node_features(feat)
    ds.init_node_labels(labels)
    train_idx = rng.permutation(n)[: int(n * 0.1)]
    return ds, train_idx


def synthetic_ppi(scale: float = 1.0, dim: int = 50, seed: int = 0,
                  graph_mode: str = "DEVICE"):
    """PPI-shaped graph for unsupervised link prediction."""
    rng = np.random.default_rng(seed)
    n = max(500, int(14_755 * scale))
    deg = 14
    indptr = (np.arange(n + 1) * deg).astype(np.int64)
    indices = rng.integers(0, n, n * deg, dtype=np.int64)
    feat = rng.normal(size=(n, dim)).astype(np.float32)
    topo = CSRTopo((indptr.astype(np.int32), indices.astype(np.int32)),
                   layout="CSR")
    from glt_tpu.data.graph import Graph

    ds = Dataset(graph=Graph(topo, mode=graph_mode,
                             with_sorted_columns=True))
    ds.init_node_features(feat)
    src, dst = topo.to_coo()
    return ds, np.stack([src, dst])


def _synthetic_citation_hetero(node_counts, relations, scale, seed,
                               graph_mode, label_type="paper", classes=8):
    """Shared builder for citation-shaped hetero benchmarks.

    ``node_counts``: type -> (floor, base) scaled by ``scale``.
    ``relations``: (src_t, rel, dst_t, degree, reversed_rel) — a reverse
    edge type is emitted whenever ``reversed_rel`` is set.  Labels live on
    ``label_type``; its features are noisy one-hot labels so the task is
    learnable, other types get pure-noise features.
    """
    rng = np.random.default_rng(seed)
    n = {t: max(floor, int(base * scale))
         for t, (floor, base) in node_counts.items()}

    ei = {}
    for src_t, rel, dst_t, deg, rev in relations:
        src = np.repeat(np.arange(n[src_t]), deg)
        dst = rng.integers(0, n[dst_t], n[src_t] * deg)
        edges = np.stack([src, dst])
        ei[(src_t, rel, dst_t)] = edges
        if rev is not None:
            ei[(dst_t, rev, src_t)] = edges[::-1]

    labels = rng.integers(0, classes, n[label_type]).astype(np.int32)
    feats = {t: rng.normal(size=(c, classes)).astype(np.float32)
             for t, c in n.items()}
    feats[label_type] = (np.eye(classes, dtype=np.float32)[labels]
                         + feats[label_type] * 0.3)
    ds = (Dataset()
          .init_graph(ei, graph_mode=graph_mode, num_nodes=n)
          .init_node_features(feats)
          .init_node_labels({label_type: labels}))
    return ds, np.arange(n[label_type]), classes


def igbh_from_disk(name: str = "igbh-tiny", graph_mode: str = "HOST"):
    """Load a converted IGB-heterogeneous dataset (scripts/convert_ogb.py
    ``igbh`` subcommand): per-type ``<type>__feat.npy`` /
    ``paper__labels.npy`` and per-relation
    ``<src>__<rel>__<dst>__edges.npy``.  Reverse edge types (``rev_<rel>``)
    are added for cross-type relations, matching the synthetic builder's
    convention.  Returns ``(ds, train_idx, classes)`` or None if absent.
    """
    root = os.path.join(DATA_ROOT, name)
    if not os.path.isdir(root):
        return None
    ei, feats, labels = {}, {}, None
    for f in sorted(os.listdir(root)):
        if not f.endswith(".npy"):
            continue
        stem = f[:-4]
        arr = np.load(os.path.join(root, f), mmap_mode="r")
        if stem.endswith("__edges"):
            src_t, rel, dst_t = stem[: -len("__edges")].split("__")
            edges = np.asarray(arr)
            ei[(src_t, rel, dst_t)] = edges
            if src_t != dst_t:
                ei[(dst_t, f"rev_{rel}", src_t)] = edges[::-1]
        elif stem.endswith("__feat"):
            feats[stem[: -len("__feat")]] = np.asarray(arr, np.float32)
        elif stem == "paper__labels":
            labels = np.asarray(arr)
    if labels is None or not ei:
        return None
    train_path = os.path.join(root, "train_idx.npy")
    train_idx = (np.asarray(np.load(train_path)) if os.path.exists(train_path)
                 else np.flatnonzero(labels >= 0))
    classes = int(labels.max()) + 1
    n = {t: f.shape[0] for t, f in feats.items()}
    ds = (Dataset()
          .init_graph(ei, graph_mode=graph_mode, num_nodes=n)
          .init_node_features(feats)
          .init_node_labels({"paper": labels.astype(np.int32)}))
    return ds, train_idx, classes


def synthetic_igbh(scale: float = 1.0, seed: int = 0,
                   graph_mode: str = "DEVICE", use_real: bool = False):
    """IGBH-tiny-shaped hetero graph: paper/author/institute.

    With ``use_real=True``, loads a converted real IGBH from
    ``DATA_ROOT/igbh-tiny`` (scripts/convert_ogb.py) — honoring the
    caller's ``graph_mode`` — and raises if it is absent.  The default
    always builds the synthetic fixture (``scale``/``seed`` honored), so
    benchmarks never silently change shape based on ambient disk state.
    """
    if use_real:
        real = igbh_from_disk("igbh-tiny", graph_mode=graph_mode)
        if real is None:
            raise FileNotFoundError(
                f"use_real=True but no converted IGBH under "
                f"{DATA_ROOT}/igbh-tiny (run scripts/convert_ogb.py)")
        return real
    return _synthetic_citation_hetero(
        {"paper": (200, 1000), "author": (150, 800), "institute": (20, 80)},
        [("paper", "cites", "paper", 4, None),
         ("author", "writes", "paper", 3, "rev_writes"),
         ("author", "affiliated", "institute", 1, "rev_affiliated")],
        scale, seed, graph_mode)


def synthetic_mag(scale: float = 1.0, seed: int = 0,
                  graph_mode: str = "DEVICE"):
    """OGB-MAG-shaped hetero graph (the reference's
    examples/hetero/train_hgt_mag.py dataset): paper / author /
    institution / field_of_study with MAG's four canonical relations
    (+ reverses), venue labels on papers."""
    return _synthetic_citation_hetero(
        {"paper": (300, 1500), "author": (200, 1000),
         "institution": (30, 100), "field_of_study": (50, 200)},
        [("paper", "cites", "paper", 4, None),
         ("author", "writes", "paper", 3, "rev_writes"),
         ("author", "affiliated_with", "institution", 1,
          "rev_affiliated_with"),
         ("paper", "has_topic", "field_of_study", 2, "rev_has_topic")],
        scale, seed, graph_mode)
